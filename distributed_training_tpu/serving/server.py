"""Serving HTTP server: a stdlib generate endpoint over the engine.

The thin network front of the serving subsystem — deliberately the
same stdlib-only discipline as ``telemetry/metrics_server.py`` (no
framework dependency for a repo whose serving claims must run in the
CI container):

- ``POST /generate`` — JSON ``{"prompt_ids": [...]}`` or (byte-vocab
  models) ``{"text": "..."}``, plus ``max_new_tokens``; blocks until
  the request drains through the continuous-batching engine and
  returns ``{"tokens", "text"?, "ttft_s", "latency_s"}``. Requests
  from many connections interleave in the engine's running batch —
  the HTTP handler threads only enqueue and wait.
- ``POST /generate`` with ``"stream": true`` — chunked
  transfer-encoding (HTTP/1.1): one JSON line per token, flushed the
  moment the engine samples it (``{"token": N}``), then a final
  ``{"done": true, "tokens", "ttft_s", "latency_s", ...}`` line.
  Tokens ride the engine's per-token listeners
  (``Engine.add_token_listener``) through a per-request queue — the
  engine thread never blocks on a slow streaming client.
- ``GET /healthz`` — 200 with queue/slot stats while the engine
  thread is alive.
- live gauges — the engine's telemetry records flow through the
  ambient sink to a ``MetricsServer`` (``metrics_port``), which
  exports the ``dtt_serving_*`` gauges next to the training set: one
  observer pattern, one ``/metrics`` schema, two workloads.

Threading model: HTTP handlers never touch the engine. They append
to a mailbox; the single engine thread admits mailbox requests,
steps the engine, and signals completion events. The engine stays
single-threaded (its allocator and jit carry no locks), and a
slow/disconnected client cannot stall decode.

CLI::

    python -m distributed_training_tpu.serving.server \
        --artifact model.msgpack --plan serving_8dev_cpu_decode \
        --port 8100 --metrics-port 8101
"""

from __future__ import annotations

import argparse
import http.server
import json
import logging
import queue
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)


def debug_requests_snapshot(engine) -> dict:
    """In-flight request table — the ``/debug/requests`` body.

    Engine bookkeeping only — slot table + page tables, zero device
    touch. Best-effort snapshot: the engine thread mutates slots
    between reads, so a sequence finishing mid-render is simply
    absent. Module-level so the incident recorder can capture the
    same snapshot into a bundle without going through HTTP."""
    reqs = []
    for s in list(engine.slots):
        if s is None:
            continue
        try:
            reqs.append({
                "id": s.req.id,
                "tenant": s.req.tenant,
                "group": engine.group_of_slot(s.slot),
                "slot": s.slot,
                "prompt_tokens": s.prompt_len,
                "prefilled": s.prefilled,
                "generated": len(s.generated),
                "pages_held":
                    engine.cache.pages_of(s.req.id),
                "session": s.req.session,
                "weights_versions": [list(p) for p in s.versions]})
        except KeyError:
            continue  # freed between reads
    return {
        "in_flight": len(reqs),
        "queue_depth": len(engine.queue),
        "draining": bool(getattr(engine, "draining", False)),
        "weights": {
            "version": engine.weights_version,
            "provenance": engine.weights_provenance,
            "swaps": dict(engine.swap_stats)},
        "requests": reqs}


class ServingServer:
    """HTTP front + engine thread over a built Engine."""

    def __init__(self, engine, port: int = 0,
                 metrics_port: int | None = None, telemetry=None,
                 max_queue_depth: int = 0,
                 retry_after_s: float = 1.0,
                 incident_dir: str | None = None):
        self.engine = engine
        self._requested_port = port
        self.port: int | None = None
        self._mailbox: list = []
        self._done: dict[str, dict] = {}
        self._events: dict[str, threading.Event] = {}
        self._streams: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd = None
        self._engine_thread = None
        self._http_thread = None
        self._next_id = 0
        self._telemetry = telemetry
        # Admission control + resilience knobs: with
        # ``max_queue_depth`` > 0, POST /generate sheds load (503 +
        # Retry-After) once queue+mailbox reach it — a bounded queue
        # beats clients silently timing out behind an unbounded one.
        # ``incident_dir`` set → an engine-thread exception leaves a
        # flight-recorder bundle there (kind ``engine_crash``).
        self.max_queue_depth = int(max_queue_depth)
        self.retry_after_s = float(retry_after_s)
        self.incident_dir = incident_dir
        # The engine thread's cause of death, when it died to an
        # exception (healthz reports "unhealthy"; new work is shed).
        self.engine_error: str | None = None
        self.leaked_threads = 0
        # Control commands (drain / weight swap) execute BETWEEN
        # steps ON the engine thread — the engine stays
        # single-threaded; public drain()/swap_weights() enqueue here
        # and wait.
        self._control: list = []
        # A MetricsServer ALWAYS backs GET /metrics on the serving
        # port (its renderer + observer, no second socket) so a
        # serving-only deployment needs no coordinator metrics port;
        # with ``metrics_port`` set the same instance additionally
        # binds the standalone endpoint the trainer convention uses.
        from distributed_training_tpu.telemetry import MetricsServer
        self._metrics_owns_port = metrics_port is not None
        self.metrics = MetricsServer(
            metrics_port if metrics_port is not None else 0,
            telemetry=telemetry)

    def debug_snapshot(self) -> dict:
        """The ``/debug/requests`` body, callable in-process — the
        incident recorder's ``serving_snapshot`` hook."""
        return debug_requests_snapshot(self.engine)

    @property
    def draining(self) -> bool:
        return bool(getattr(self.engine, "draining", False))

    def _control_call(self, cmd: str, args,
                      timeout: float = 300.0):
        """Run a drain/swap command ON the engine thread (started
        server) or inline (engine thread not running — the in-process
        test path); either way the engine is only ever touched from
        one thread at a time."""
        t = self._engine_thread
        if t is None or not t.is_alive():
            done = threading.Event()
            slot: dict = {}
            self._control.append((cmd, args, done, slot))
            self._run_control(self.engine)
        else:
            done = threading.Event()
            slot = {}
            with self._lock:
                self._control.append((cmd, args, done, slot))
            if not done.wait(timeout):
                raise TimeoutError(f"{cmd} command timed out after "
                                   f"{timeout}s")
        if "error" in slot:
            raise slot["error"]
        return slot.get("result")

    def swap_weights(self, params, version: str,
                     provenance: dict | None = None,
                     timeout: float = 300.0):
        """Live weight hot-swap through the engine thread
        (``Engine.swap_weights`` — all gates, zero recompiles).
        Raises the engine's refusal verbatim; the incumbent weights
        keep serving on any failure."""
        return self._control_call("swap", (params, version,
                                           provenance), timeout)

    def drain(self, deadline_s: float | None = None,
              timeout: float = 300.0) -> dict:
        """Graceful drain through the engine thread: admission stops
        (POST /generate starts 503ing with Retry-After, /healthz
        reports "draining"), in-flight work finishes (or persists at
        the deadline), and the per-request outcome report returns.
        ``resume_admission()`` reopens the front door."""
        return self._control_call("drain", deadline_s,
                                  max(timeout, (deadline_s or 0) * 2))

    def resume_admission(self, timeout: float = 60.0) -> None:
        self._control_call("undrain", None, timeout)

    # -- engine thread -----------------------------------------------------

    def _engine_loop(self) -> None:
        from distributed_training_tpu.serving.engine import Request

        eng = self.engine
        try:
            self._engine_loop_inner(eng, Request)
        except Exception as e:  # noqa: BLE001 — the engine thread's
            # last act: record WHY it died (bundle + event + error
            # replies) instead of dying silently with every in-flight
            # client blocked until timeout.
            self._on_engine_crash(e)

    def _on_engine_crash(self, exc: Exception) -> None:
        """Engine-thread postmortem: mark unhealthy, fail every
        waiting client, emit ``serving_engine_crash``, and (with
        ``incident_dir``) leave a flight-recorder bundle carrying the
        ``/debug/requests`` snapshot and the last weight-swap
        provenance — the evidence ``--doctor`` classifies as
        ``serving_engine_crash``."""
        from distributed_training_tpu import telemetry as tel

        err = f"{type(exc).__name__}: {exc}"
        self.engine_error = err
        logger.exception("serving engine thread died: %s", err)
        eng = self.engine
        snap = None
        try:
            snap = debug_requests_snapshot(eng)
        except Exception:  # noqa: BLE001 — evidence is best-effort;
            # the postmortem must survive a half-broken engine.
            logger.warning("debug snapshot failed during crash "
                           "postmortem", exc_info=True)
        # Event BEFORE the bundle so its events_tail carries the
        # record the doctor keys on.
        tel.event("serving_engine_crash", error=err,
                  launches=getattr(eng, "launch_count", None),
                  weights_version=getattr(eng, "weights_version",
                                          None),
                  in_flight=eng.in_flight,
                  queue_depth=len(eng.queue))
        if self.incident_dir:
            from distributed_training_tpu.telemetry.incident import (
                write_incident_bundle)
            write_incident_bundle(
                self.incident_dir, reason=err, kind="engine_crash",
                events_tail=tel.current().tail(),
                extra={"launch_count": getattr(eng, "launch_count",
                                               None),
                       "weights_version": getattr(
                           eng, "weights_version", None),
                       "weights_provenance": getattr(
                           eng, "weights_provenance", None),
                       "swap_stats": dict(getattr(eng, "swap_stats",
                                                  {}))},
                serving=snap)
        with self._lock:
            events, self._events = self._events, {}
            streams, self._streams = self._streams, {}
            for rid, ev in events.items():
                self._done[rid] = {"id": rid,
                                   "error": f"engine crashed: {err}"}
                ev.set()
        for rid, sq in streams.items():
            sq.put(("done", {"id": rid,
                             "error": f"engine crashed: {err}"}))

    def _run_control(self, eng) -> None:
        """Execute queued drain/swap commands on the engine thread.
        Results (or the refusal exception) hand back through each
        command's slot; the caller re-raises in its own thread."""
        with self._lock:
            cmds, self._control = self._control, []
        for cmd, args, done, slot in cmds:
            try:
                if cmd == "swap":
                    params, version, provenance = args
                    slot["result"] = eng.swap_weights(
                        params, version, provenance)
                elif cmd == "drain":
                    slot["result"] = eng.drain(args)
                elif cmd == "undrain":
                    eng.draining = False
                    slot["result"] = True
            except Exception as e:  # noqa: BLE001 — a REFUSED swap
                # must reach its caller, never kill the engine
                # thread (the engine still serves the incumbent).
                slot["error"] = e
            finally:
                done.set()

    def _engine_loop_inner(self, eng, Request) -> None:
        while not self._stop.is_set():
            self._run_control(eng)
            with self._lock:
                incoming, self._mailbox = self._mailbox, []
            for rid, prompt, n, arrival, session, tenant \
                    in incoming:
                with self._lock:
                    stream_q = self._streams.get(rid)
                if stream_q is not None:
                    # Registered BEFORE submit, on the engine thread:
                    # the first token cannot race its listener.
                    eng.add_token_listener(
                        rid,
                        lambda tok, done, _q=stream_q:
                            _q.put(("token", tok)))
                try:
                    eng.submit(Request(id=rid, prompt=prompt,
                                       max_new_tokens=n,
                                       arrival=arrival,
                                       session=session,
                                       tenant=tenant))
                except ValueError as e:
                    # An invalid request answers ITS caller; it must
                    # never take down the engine thread (and with it
                    # every other in-flight request).
                    eng.remove_token_listener(rid)
                    with self._lock:
                        ev = self._events.pop(rid, None)
                        if ev is not None:
                            self._done[rid] = {"id": rid,
                                               "error": str(e)}
                            ev.set()
                        sq = self._streams.pop(rid, None)
                    if sq is not None:
                        sq.put(("done", {"id": rid,
                                         "error": str(e)}))
            # Dispatch BEFORE the idle check too: a drain command
            # finishes requests inside _run_control, and their
            # waiting clients must not hang on an idle engine.
            self._dispatch_completed(eng)
            if eng.idle:
                time.sleep(0.002)
                continue
            eng.step()
            self._dispatch_completed(eng)

    def _dispatch_completed(self, eng) -> None:
        if not eng.completed:
            return
        with self._lock:
            for rec in eng.completed:
                ev = self._events.pop(rec["id"], None)
                if ev is not None:
                    self._done[rec["id"]] = rec
                    ev.set()
                sq = self._streams.pop(rec["id"], None)
                if sq is not None:
                    sq.put(("done", rec))
        eng.completed.clear()

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 timeout: float = 120.0,
                 session: str | None = None,
                 tenant: str = "default") -> dict:
        """Enqueue + wait (the HTTP handler path; also the in-process
        API tests use). ``session``: chat-session key — the engine
        retains the turn's KV pages under it and a follow-up call
        with the same key resumes with zero prefill for the retained
        history (serving/engine.py). ``tenant``: accounting label for
        the per-tenant latency histograms and trace records."""
        arrival = time.monotonic()
        ev = threading.Event()
        with self._lock:
            rid = f"http-{self._next_id}"
            self._next_id += 1
            self._events[rid] = ev
            self._mailbox.append((rid, np.array(prompt, np.int32),
                                  int(max_new_tokens), arrival,
                                  session, tenant))
        if not ev.wait(timeout):
            with self._lock:
                # Deregister so a late completion is dropped instead
                # of accumulating forever in _done.
                self._events.pop(rid, None)
                self._done.pop(rid, None)
            raise TimeoutError(f"request {rid} timed out")
        with self._lock:
            return self._done.pop(rid)

    def generate_stream(self, prompt: np.ndarray,
                        max_new_tokens: int,
                        timeout: float = 120.0,
                        session: str | None = None,
                        tenant: str = "default"):
        """Enqueue + yield per-token dicts as the engine produces
        them: ``{"token": N}`` per sampled token, then a final
        ``{"done": True, "tokens", "ttft_s", "latency_s"}``. The
        tokens flow engine thread → per-request queue → this
        generator, so a slow consumer never stalls decode."""
        arrival = time.monotonic()
        q: queue.Queue = queue.Queue()
        with self._lock:
            rid = f"http-{self._next_id}"
            self._next_id += 1
            self._streams[rid] = q
            self._mailbox.append((rid, np.array(prompt, np.int32),
                                  int(max_new_tokens), arrival,
                                  session, tenant))
        deadline = time.monotonic() + timeout
        try:
            while True:
                try:
                    kind, val = q.get(
                        timeout=max(0.0,
                                    deadline - time.monotonic()))
                except queue.Empty:
                    raise TimeoutError(
                        f"request {rid} timed out mid-stream"
                    ) from None
                if kind == "token":
                    yield {"token": int(val)}
                    continue
                if "error" in val:
                    raise ValueError(val["error"])
                out = {"done": True, "tokens": val["tokens"],
                       "ttft_s": val["ttft_s"],
                       "latency_s": val["latency_s"]}
                if self.engine.model.cfg.vocab_size == 256:
                    out["text"] = bytes(
                        np.array(val["tokens"], np.uint8)).decode(
                            "utf-8", errors="replace")
                yield out
                return
        finally:
            # Runs on completion, timeout, AND abandonment (the
            # handler close()s the generator when the client
            # disconnects mid-stream): without the deregistration
            # the engine-side listener keeps filling an orphaned
            # queue until the sequence drains. Idempotent — the
            # engine loop pops both on normal completion too.
            with self._lock:
                self._streams.pop(rid, None)
            self.engine.remove_token_listener(rid)

    # -- HTTP --------------------------------------------------------------

    def _parse_generate(self, body: dict):
        """Validate a /generate body → (prompt_ids, max_new_tokens,
        session, tenant). Raises ValueError (the 400 path) BEFORE
        anything reaches the engine — the streaming handler needs
        every rejection to happen while the status line is still
        writable."""
        vocab = self.engine.model.cfg.vocab_size
        if "prompt_ids" in body:
            ids = np.array([int(t) for t in body["prompt_ids"]],
                             np.int32)
        elif "text" in body:
            if vocab != 256:
                raise ValueError(
                    "'text' prompts need a byte-vocab (256) model; "
                    "pass 'prompt_ids'")
            ids = np.frombuffer(
                body["text"].encode("utf-8"),
                dtype=np.uint8).astype(np.int32)
        else:
            raise ValueError("body needs 'prompt_ids' or 'text'")
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size and (ids.min() < 0 or ids.max() >= vocab):
            raise ValueError(f"prompt ids must be in [0, {vocab})")
        n = int(body.get("max_new_tokens", 16))
        limit = self.engine.cfg.max_seq_len
        if n < 1 or ids.size + n > limit:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({n}) must "
                f"fit max_seq_len ({limit})")
        session = body.get("session")
        if session is not None and not isinstance(session, str):
            raise ValueError("'session' must be a string key")
        tenant = body.get("tenant", "default")
        if not isinstance(tenant, str) or not tenant:
            raise ValueError("'tenant' must be a non-empty string")
        return ids, n, session, tenant

    def _handle_generate(self, body: dict) -> dict:
        ids, n, session, tenant = self._parse_generate(body)
        rec = self.generate(ids, n, session=session, tenant=tenant)
        if "error" in rec:
            raise ValueError(rec["error"])
        out = {"tokens": rec["tokens"], "ttft_s": rec["ttft_s"],
               "latency_s": rec["latency_s"]}
        if self.engine.model.cfg.vocab_size == 256:
            out["text"] = bytes(
                np.array(rec["tokens"], np.uint8)).decode(
                    "utf-8", errors="replace")
        return out

    def start(self) -> "ServingServer | None":
        from distributed_training_tpu.telemetry.metrics_server \
            import PROM_CONTENT_TYPE

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # Chunked transfer-encoding (the streaming path) is an
            # HTTP/1.1 construct; non-stream replies always carry
            # Content-Length, so keep-alive semantics stay valid.
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, payload: dict,
                       headers: tuple = ()) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                # One request per connection: clients here are
                # one-shot, and a dangling keep-alive socket at
                # server stop() surfaces as handler-thread noise.
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
                self.wfile.write(body)

            def _shed(self) -> dict | None:
                """Load-shedding gate for POST /generate: 503 +
                Retry-After while draining, after an engine crash,
                or past the configured queue depth — a bounded
                refusal beats queuing until the client times out."""
                eng = server.engine
                if server.engine_error is not None:
                    return {"error": "engine crashed: "
                                     + server.engine_error}
                if server.draining:
                    return {"error": "draining: not admitting new "
                                     "requests"}
                if server.max_queue_depth > 0:
                    with server._lock:
                        depth = (len(eng.queue)
                                 + len(server._mailbox))
                    if depth >= server.max_queue_depth:
                        return {"error": "queue full "
                                         f"(depth {depth} >= "
                                         f"{server.max_queue_depth})"}
                return None

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(f"{len(data):X}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()

            def _stream_generate(self, body: dict) -> None:
                try:
                    ids, n, session, tenant = \
                        server._parse_generate(body)
                except (ValueError, KeyError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                gen = server.generate_stream(ids, n,
                                             session=session,
                                             tenant=tenant)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
                try:
                    for item in gen:
                        self._chunk((json.dumps(item) + "\n")
                                    .encode())
                except (ValueError, TimeoutError) as e:
                    # Headers are gone; the error becomes the
                    # stream's last line (best-effort — the client
                    # may already be gone).
                    try:
                        self._chunk((json.dumps(
                            {"error": str(e)}) + "\n").encode())
                    except OSError:
                        pass
                except OSError:
                    # Client disconnected mid-stream; nobody left
                    # to tell.
                    pass
                finally:
                    # close() reaches generate_stream's finally so
                    # the engine-side listener is deregistered even
                    # when the stream is abandoned.
                    gen.close()
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass

            def do_POST(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] != "/generate":
                    self._reply(404, {"error": "try POST /generate"})
                    return
                shed = self._shed()
                if shed is not None:
                    self._reply(503, shed, headers=(
                        ("Retry-After",
                         str(max(1, int(server.retry_after_s)))),))
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, KeyError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                if body.get("stream"):
                    self._stream_generate(body)
                    return
                try:
                    self._reply(200, server._handle_generate(body))
                except (ValueError, KeyError) as e:
                    self._reply(400, {"error": str(e)})
                except TimeoutError as e:
                    self._reply(504, {"error": str(e)})

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?")[0]
                eng = server.engine
                if path == "/healthz":
                    # Tri-state: "unhealthy" (503) when the engine
                    # thread died, "draining" (200 — the pod is
                    # healthy, just not admitting) during a drain,
                    # else "ok".
                    alive = (server._engine_thread is not None
                             and server._engine_thread.is_alive())
                    if server.engine_error is not None or not alive:
                        status, code = "unhealthy", 503
                    elif server.draining:
                        status, code = "draining", 200
                    else:
                        status, code = "ok", 200
                    self._reply(code, {
                        "status": status,
                        "error": server.engine_error,
                        "in_flight": eng.in_flight,
                        "queue_depth": len(eng.queue),
                        "weights_version": eng.weights_version,
                        **eng.cache.occupancy()})
                    return
                if path == "/metrics":
                    body = server.metrics.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROM_CONTENT_TYPE)
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.send_header("Connection", "close")
                    self.close_connection = True
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/debug/requests":
                    self._reply(200, debug_requests_snapshot(eng))
                    return
                self._reply(404, {"error": "try /healthz, /metrics "
                                           "or /debug/requests"})

            def log_message(self, fmt, *args):
                logger.debug("serving http: " + fmt, *args)

        try:
            self._httpd = http.server.ThreadingHTTPServer(
                ("0.0.0.0", self._requested_port), Handler)
        except OSError as e:
            logger.warning("serving endpoint NOT started (port %s): "
                           "%s", self._requested_port, e)
            return None
        self.port = self._httpd.server_address[1]
        if self._metrics_owns_port:
            self.metrics.start()
        else:
            # Renderer-only mode: no second socket, but the observer
            # must still fold records so GET /metrics on THIS port
            # has data (MetricsServer.start() normally registers it
            # post-bind). The engine emits through the AMBIENT sink
            # when none was passed explicitly, so observe that one;
            # the disabled default sink never calls observers, which
            # degrades to an empty (but valid) exposition.
            from distributed_training_tpu.telemetry import current
            tel = self._telemetry if self._telemetry is not None \
                else current()
            tel.add_observer(self.metrics.observe)
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="serving-engine",
            daemon=True)
        self._engine_thread.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._http_thread.start()
        logger.info("serving endpoint on :%d (POST /generate)",
                    self.port)
        return self

    def stop(self) -> None:
        """Stop the HTTP front + engine thread. Thread joins carry a
        5 s timeout — a wedged engine step must not hang teardown —
        but a straggler is COUNTED, not silently leaked: the
        ``serving_stop`` telemetry event reports ``leaked_threads``
        (0 after every clean stop, pinned by test) so a leak shows in
        the stream instead of as mystery state in the next test."""
        from distributed_training_tpu import telemetry as tel

        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.metrics is not None:
            self.metrics.stop()
        leaked = []
        for t in (self._engine_thread, self._http_thread):
            if t is not None:
                t.join(timeout=5)
                if t.is_alive():
                    leaked.append(t.name)
        self.leaked_threads = len(leaked)
        if leaked:
            logger.warning("serving stop leaked %d thread(s): %s",
                           len(leaked), ", ".join(leaked))
        tel.event("serving_stop", leaked_threads=len(leaked),
                  leaked=leaked,
                  engine_error=self.engine_error)
        self._engine_thread = self._http_thread = None


def engine_config_from_yaml(plan, engine_block: dict):
    """conf/serving/*.yaml ``engine:`` block → EngineConfig, with 0
    meaning "take the plan's value" (engine_config_for_plan)."""
    import dataclasses

    from distributed_training_tpu.serving.disagg import (
        engine_config_for_plan)

    base = engine_config_for_plan(
        plan,
        page_size=int(engine_block.get("page_size", 16)),
        prefill_chunk=int(engine_block.get("prefill_chunk", 16)))
    # 0 / empty = "keep the plan-derived value" for every knob
    # (temperature 0 IS the plan-derived greedy default;
    # prefill_slots 0 means "same table as max_batch" and spec_k 1
    # is plain one-token decode, so both pass through replace()
    # harmlessly when set).
    over = {k: v for k, v in engine_block.items()
            if k in ("max_batch", "num_pages", "max_seq_len",
                     "policy", "temperature", "top_k",
                     "prefill_slots", "prefill_mode", "spec_k",
                     "spec_ngram", "resident_k", "eos_id")
            and v not in (0, 0.0, None, "")}
    # prefix_sharing is a REAL boolean: False == 0 would fall into
    # the "keep default" filter above and silently re-enable it.
    if "prefix_sharing" in engine_block \
            and engine_block["prefix_sharing"] is not None:
        over["prefix_sharing"] = bool(engine_block["prefix_sharing"])
    # swap_staleness_tokens: 0 is a MEANINGFUL bound (resubmit every
    # in-flight request at swap time), so it must dodge the 0-filter;
    # -1/absent = unbounded.
    if "swap_staleness_tokens" in engine_block \
            and engine_block["swap_staleness_tokens"] is not None:
        over["swap_staleness_tokens"] = int(
            engine_block["swap_staleness_tokens"])
    return dataclasses.replace(base, **over)


def build_server(artifact: str, plan_name: str, port: int = 0,
                 metrics_port: int | None = None,
                 telemetry=None,
                 engine_block: dict | None = None,
                 server_block: dict | None = None) -> ServingServer:
    """Artifact + committed plan → laid-out engine → server.

    The provenance gate lives in WeightStore: an artifact whose
    recorded source plan no longer matches its committed fingerprint
    refuses to serve (serving/disagg.py)."""
    import jax

    from distributed_training_tpu.parallel.planner import (
        load_plan, model_for_plan)
    from distributed_training_tpu.runtime import build_mesh, MeshSpec
    from distributed_training_tpu.serving.disagg import WeightStore
    from distributed_training_tpu.serving.engine import Engine

    plan = load_plan(plan_name)
    store = WeightStore(artifact)
    model = model_for_plan(plan)
    spec = MeshSpec(**{a: plan.mesh.get(a, 1)
                       for a in ("pp", "dp", "fsdp", "sp", "tp")})
    mesh = build_mesh(spec, jax.devices()[:spec.total])
    ecfg = engine_config_from_yaml(plan, engine_block or {})
    engine = Engine(model, store.params_for(mesh, plan), ecfg,
                    mesh=mesh,
                    weights_provenance=store.provenance)
    engine.warmup()
    sb = server_block or {}
    return ServingServer(
        engine, port=port, metrics_port=metrics_port,
        telemetry=telemetry,
        max_queue_depth=int(sb.get("max_queue_depth", 0) or 0),
        retry_after_s=float(sb.get("retry_after_s", 1.0) or 1.0),
        incident_dir=sb.get("incident_dir"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_training_tpu.serving.server",
        description="Continuous-batching inference server.")
    ap.add_argument("--artifact", required=True,
                    help="consolidated export (checkpoint/export.py)")
    ap.add_argument("--plan", default=None,
                    help="committed decode plan name (conf/plans/); "
                         "default: the --config file's plan")
    ap.add_argument("--config", default=None,
                    help="serving YAML (conf/serving/default.yaml): "
                         "engine geometry, scheduling policy, ports; "
                         "explicit flags win per key")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--metrics-port", type=int, default=None)
    args = ap.parse_args(argv)

    conf: dict = {}
    if args.config:
        import yaml
        with open(args.config) as f:
            conf = yaml.safe_load(f) or {}
    plan_name = args.plan or conf.get("plan")
    if not plan_name:
        ap.error("no plan: pass --plan or a --config with one")
    srv_conf = conf.get("server") or {}
    port = args.port if args.port is not None \
        else int(srv_conf.get("port", 8100))
    mp_conf = srv_conf.get("metrics_port", 8101)
    # metrics_port: null in the config = no standalone endpoint; the
    # serving port's own GET /metrics still works (renderer-only).
    metrics_port = args.metrics_port if args.metrics_port is not None \
        else (int(mp_conf) if mp_conf is not None else None)

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])
    from distributed_training_tpu.telemetry import (Telemetry,
                                                    install)
    # The sink must be ENABLED (jsonl-backed) for the observer chain
    # to fire — a disabled Telemetry emits nothing and the gauges
    # would stay empty (telemetry/events.py::_emit's fast path).
    tel = install(Telemetry(events_jsonl=os.path.join(
        "outputs", "serving", "events.jsonl")))
    if not srv_conf.get("incident_dir"):
        srv_conf = {**srv_conf,
                    "incident_dir": os.path.join(
                        "outputs", "serving", "incidents")}
    srv = build_server(args.artifact, plan_name, port=port,
                       metrics_port=metrics_port, telemetry=tel,
                       engine_block=conf.get("engine") or {},
                       server_block=srv_conf)
    if srv.start() is None:
        return 1
    print(f"serving on :{srv.port} (metrics :{metrics_port}); "
          "Ctrl-C to stop")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
