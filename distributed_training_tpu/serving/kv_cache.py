"""Paged KV cache: fixed-size pages in one preallocated, sharded pool.

Per-request max_len buffers waste HBM quadratically under continuous
batching (every slot reserves the worst case); the paged layout is
virtual memory for KV instead. One reservation of ``dp_groups``
independent pool shards of ``num_pages`` pages of ``page_size`` tokens
each, per layer, kv-head-major:

    k_pages, v_pages: (dp_groups, n_layers, n_kv_heads, num_pages,
                       page_size, head_dim)

A sequence owns an ordered list of physical page ids (its PAGE TABLE)
inside ONE dp group's shard; logical position ``p`` lives in slot
``p % page_size`` of its ``p // page_size``-th page. Join = allocate
pages from the group's free list, evict = return them — no copying, no
compaction, and the device arrays never change shape, so the decode
program never recompiles.

**Page 0 of every group is that group's scratch page**: never
allocated, the write target for inactive batch slots and padding
positions (the jitted decode/prefill programs write unconditionally;
pointing dead writes at scratch keeps them out of live pages without
dynamic shapes). Unused page-table entries also point at it — their
slots are masked out of attention by position, so the garbage is never
read into a softmax.

**Sharding**: on a multi-device mesh the pool is sharded along the
LEADING dp-group axis over the plan's ``dp`` mesh axis (the decode
engine's batch-parallel slot shard — each dp group decodes only its
own slots against its own pool shard, serving/engine.py) and along the
kv-head axis over the plan's ``tp`` axis (the decode plan's head
currency), replicated elsewhere. Page tables/lengths are tiny int32
rows and stay host-side.

**Accounting**: the allocator is host-side (plain Python — allocation
decisions are control flow, not math), PER GROUP, and every alloc/free
emits a ``serving_kv`` telemetry record with the pool occupancy AND
the owning group, which the metrics endpoint folds into
``dtt_serving_kv_pages_{used,total}`` plus the per-group labeled
gauges. Invariant (pinned by test, per shard): for every group,
``pages_used_in(g) + free == num_pages - 1`` always, and freeing every
sequence returns every group's occupancy to zero — no join/evict order
can leak a page or let one group's allocation bleed into another's
shard.

**Sharing (SERVING_r05)**: pages are REFCOUNTED per (group, page).
``attach`` lets a new sequence take read-only references on another
sequence's committed pages (its table becomes a view of the shared
prefix); ``free`` returns a page to the free list only when its LAST
owner releases it, so the leak invariant extends unchanged — a page is
"used" while any table holds it. A group-local PREFIX INDEX maps the
exact bytes of each page-aligned token prefix to the page ids holding
its KV (``register_prefix``/``match_prefix``); entries are registered
only for FULLY COMMITTED pages (every slot written, so the content is
immutable — later writes go through copy-on-write) and invalidated
when their last page's refcount hits zero. ``privatize`` is the COW
half: before a sequence writes into a page it shares (only the page at
``length // page_size`` can qualify — committed pages below it are
never written again), the shared page is swapped for a fresh private
one and the caller performs the one batched device copy. ``rename``
moves a table between owner keys without touching refcounts — the
engine's session retention (a finished chat turn parks its pages under
a session key for zero-prefill resume).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_training_tpu.telemetry import event


@dataclass(frozen=True)
class PagedCacheConfig:
    """Pool geometry. ``max_seq_len`` bounds pages per sequence;
    ``num_pages`` is PER GROUP (each dp group owns its own shard of
    ``num_pages`` pages, scratch included)."""

    n_layers: int
    n_kv_heads: int
    head_dim: int
    page_size: int = 16
    num_pages: int = 128          # per group, scratch page 0 included
    max_seq_len: int = 256
    dtype: str = "float32"
    dp_groups: int = 1            # leading pool dim / allocator shards

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got "
                             f"{self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is scratch), got "
                f"{self.num_pages}")
        if self.max_seq_len % self.page_size:
            raise ValueError(
                f"max_seq_len ({self.max_seq_len}) must be a multiple "
                f"of page_size ({self.page_size})")
        if self.dp_groups < 1:
            raise ValueError(
                f"dp_groups must be >= 1, got {self.dp_groups}")

    @property
    def pages_per_seq(self) -> int:
        return self.max_seq_len // self.page_size

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # per group, minus scratch

    @property
    def usable_pages_total(self) -> int:
        return self.dp_groups * self.usable_pages

    def kv_bytes_per_token(self) -> int:
        """HBM cost of one cached token across all layers (k + v)."""
        itemsize = np.dtype(self.dtype).itemsize
        return (2 * self.n_layers * self.n_kv_heads * self.head_dim
                * itemsize)


def pool_sharding(mesh, n_kv_heads: int, dp_groups: int,
                  kv_axis: str | None, dp_axis: str | None):
    """The pool's NamedSharding on ``mesh`` (None when no mesh):
    leading group dim over ``dp_axis``, kv-head dim over ``kv_axis``,
    each when its extent > 1. ONE resolution shared by the cache's
    device_put and the engine's program ``out_shardings``
    (serving/engine.py) — if they disagreed, every step's donated
    pool would come back in a different layout and the decode program
    would recompile mid-storm."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    kv_ax = kv_axis if kv_axis and sizes.get(kv_axis, 1) > 1 else None
    if kv_ax is not None and n_kv_heads % sizes[kv_ax]:
        raise ValueError(
            f"kv pool cannot shard {n_kv_heads} kv heads over "
            f"{kv_axis}={sizes[kv_ax]}")
    dp_ax = dp_axis if dp_axis and sizes.get(dp_axis, 1) > 1 else None
    if dp_ax is not None and dp_groups != sizes[dp_ax]:
        raise ValueError(
            f"pool has {dp_groups} dp group(s) but mesh axis "
            f"'{dp_axis}' has extent {sizes[dp_ax]} — the allocator "
            "groups must be the mesh's dp groups")
    return NamedSharding(mesh, P(dp_ax, None, kv_ax))


class PagedKVCache:
    """The pool + its per-group host-side allocators and page tables.

    ``mesh``/``kv_axis``/``dp_axis``: shard the pools' kv-head dim
    over ``kv_axis`` and the leading group dim over ``dp_axis``
    (either skipped when its axis has extent 1 or no mesh is given).
    ``cfg.dp_groups`` must equal the ``dp_axis`` extent when that axis
    is sharded — the allocator groups ARE the mesh's dp groups. The
    device pools are handed to the engine's jitted programs as donated
    inputs; the engine writes the updated arrays back via
    ``update_pools`` each step.
    """

    def __init__(self, cfg: PagedCacheConfig, mesh=None,
                 kv_axis: str | None = None,
                 dp_axis: str | None = "dp"):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        shape = (cfg.dp_groups, cfg.n_layers, cfg.n_kv_heads,
                 cfg.num_pages, cfg.page_size, cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        self.sharding = sharding = pool_sharding(
            mesh, cfg.n_kv_heads, cfg.dp_groups, kv_axis, dp_axis)

        def pool():
            # Two DISTINCT buffers: k and v are donated separately to
            # the jitted programs, and donating one aliased array
            # twice is an XLA error.
            z = jnp.zeros(shape, dt)
            return jax.device_put(z, sharding) \
                if sharding is not None else z

        self.k_pages = pool()
        self.v_pages = pool()
        # Host allocator state, PER GROUP. Free lists are LIFO:
        # recently-freed pages are re-handed first (warm in cache, and
        # deterministic for the tests' join/evict permutations).
        self._frees: list[list[int]] = [
            list(range(cfg.num_pages - 1, 0, -1))
            for _ in range(cfg.dp_groups)]
        self._tables: dict[object, list[int]] = {}
        self._lengths: dict[object, int] = {}
        self._groups: dict[object, int] = {}
        # Sharing state, PER GROUP. ``_refs[g][page]`` counts the
        # tables holding ``page`` (absent == on the free list);
        # ``_index[g]`` maps the exact bytes of a page-aligned token
        # prefix to the page ids holding its KV; ``_page_keys[g]``
        # maps a page id to the index keys whose LAST page it is (a
        # key dies exactly when its last page is released — earlier
        # pages outlive it by the prefix-holding property, so one
        # reverse entry per key suffices). ``_registered`` tracks how
        # many of each sequence's pages are already in the index.
        self._refs: list[dict[int, int]] = [
            {} for _ in range(cfg.dp_groups)]
        self._index: list[dict[bytes, tuple]] = [
            {} for _ in range(cfg.dp_groups)]
        self._page_keys: list[dict[int, set]] = [
            {} for _ in range(cfg.dp_groups)]
        self._registered: dict[object, int] = {}

    # -- allocator ---------------------------------------------------------

    @property
    def _free(self) -> list[int]:
        """Group 0's free list — the PR-13 single-pool surface, kept
        for the unsharded (dp_groups == 1) callers and tests."""
        if self.cfg.dp_groups != 1:
            raise AttributeError(
                "no single free list on a dp-sharded pool — use "
                "free_pages_in(group)")
        return self._frees[0]

    def free_pages_in(self, group: int) -> int:
        return len(self._frees[group])

    @property
    def pages_used(self) -> int:
        """Pages allocated across ALL groups."""
        return self.cfg.usable_pages_total - sum(
            len(f) for f in self._frees)

    def pages_used_in(self, group: int) -> int:
        return self.cfg.usable_pages - len(self._frees[group])

    @property
    def seqs(self) -> int:
        return len(self._tables)

    def seqs_in(self, group: int) -> int:
        return sum(1 for g in self._groups.values() if g == group)

    def _emit(self, op: str, seq_id) -> None:
        event("serving_kv", op=op, seq=str(seq_id),
              group=self._groups.get(seq_id, 0),
              pages_used=self.pages_used,
              pages_total=self.cfg.usable_pages_total,
              seqs=self.seqs)

    def can_admit(self, n_tokens: int, group: int = 0) -> bool:
        """Would ``ensure`` succeed for a NEW sequence of n_tokens in
        ``group``?"""
        need = -(-max(1, n_tokens) // self.cfg.page_size)
        return need <= len(self._frees[group])

    def join(self, seq_id, group: int = 0) -> None:
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already joined")
        if not 0 <= group < self.cfg.dp_groups:
            raise ValueError(
                f"group {group} out of range (pool has "
                f"{self.cfg.dp_groups} dp group(s))")
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0
        self._groups[seq_id] = group
        self._emit("join", seq_id)

    def group_of(self, seq_id) -> int:
        return self._groups[seq_id]

    def ensure(self, seq_id, n_tokens: int) -> bool:
        """Grow seq_id's table to cover ``n_tokens`` total positions,
        from its OWN group's free list. Returns False (allocating
        NOTHING — admission is atomic per call) when that free list
        cannot cover the growth; the engine treats that as
        backpressure and defers the work."""
        if n_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"sequence {seq_id!r} needs {n_tokens} positions, "
                f"pool max_seq_len is {self.cfg.max_seq_len}")
        table = self._tables[seq_id]
        group = self._groups[seq_id]
        free = self._frees[group]
        need = -(-n_tokens // self.cfg.page_size) - len(table)
        if need <= 0:
            return True
        if need > len(free):
            return False
        refs = self._refs[group]
        for _ in range(need):
            page = free.pop()
            refs[page] = 1
            table.append(page)
        self._emit("grow", seq_id)
        return True

    def advance(self, seq_id, n_tokens: int) -> None:
        """Record ``n_tokens`` more positions as written (pages must
        already be ensured)."""
        new_len = self._lengths[seq_id] + n_tokens
        table = self._tables[seq_id]
        if new_len > len(table) * self.cfg.page_size:
            raise RuntimeError(
                f"sequence {seq_id!r}: advancing to {new_len} "
                f"positions but only {len(table)} page(s) allocated "
                "— ensure() first")
        self._lengths[seq_id] = new_len

    def free(self, seq_id) -> int:
        """Evict: drop one reference on each of the sequence's pages;
        pages whose LAST reference this was go back to the group's
        free list (and their prefix-index entries die with them).
        Returns the page count actually released."""
        table = self._tables.pop(seq_id)
        del self._lengths[seq_id]
        group = self._groups[seq_id]
        refs = self._refs[group]
        released = []
        for page in table:
            refs[page] -= 1
            if refs[page] == 0:
                del refs[page]
                self._invalidate(group, page)
                released.append(page)
        self._frees[group].extend(reversed(released))
        self._registered.pop(seq_id, None)
        self._emit("free", seq_id)
        del self._groups[seq_id]
        return len(released)

    def length(self, seq_id) -> int:
        return self._lengths[seq_id]

    def pages_of(self, seq_id) -> int:
        """Pages in ``seq_id``'s table (shared pages count — they are
        held, refcounted). The /debug/requests introspection read;
        raises KeyError for unknown ids like every per-seq accessor."""
        return len(self._tables[seq_id])

    # -- sharing: refcounted attach / COW / prefix index -------------------

    def attach(self, seq_id, pages, n_tokens: int) -> None:
        """Take read-only references on ``pages`` (an existing
        resident prefix, in table order) for a JOINED sequence with an
        EMPTY table, and mark ``n_tokens`` positions as already
        written. The pages must be live in the sequence's group —
        attaching a freed page is a hard error, not a silent
        corruption."""
        table = self._tables[seq_id]
        if table or self._lengths[seq_id]:
            raise RuntimeError(
                f"sequence {seq_id!r} already has pages — attach is "
                "admission-time only")
        if n_tokens > len(pages) * self.cfg.page_size:
            raise ValueError(
                f"sequence {seq_id!r}: attaching {len(pages)} page(s) "
                f"cannot cover {n_tokens} positions")
        refs = self._refs[self._groups[seq_id]]
        for page in pages:
            refs[page] = refs[page] + 1  # KeyError if not live
        table.extend(pages)
        self._lengths[seq_id] = n_tokens
        # The attached prefix is already indexed (it came FROM the
        # index or a session table) — start registration past it.
        self._registered[seq_id] = len(pages)
        self._emit("attach", seq_id)

    def rename(self, old_id, new_id) -> None:
        """Move a table between owner keys (refcounts untouched) —
        session retention parks a finished sequence's pages under its
        session key; resume renames them back."""
        if new_id in self._tables:
            raise KeyError(f"sequence {new_id!r} already joined")
        self._tables[new_id] = self._tables.pop(old_id)
        self._lengths[new_id] = self._lengths.pop(old_id)
        self._groups[new_id] = self._groups.pop(old_id)
        if old_id in self._registered:
            self._registered[new_id] = self._registered.pop(old_id)

    def privatize(self, seq_id):
        """Copy-on-write bookkeeping: swap every SHARED page at or
        past the sequence's write frontier (``length // page_size``)
        for a fresh private page. Returns the ``(src, dst)`` page-id
        pairs for the caller's batched device copy ([] when nothing
        was shared), or None — allocating nothing — when the free list
        cannot cover the swap (backpressure, same contract as
        ``ensure``). Only the frontier page can be both shared and
        written (pages below it are fully committed and never written
        again), so this is at most one pair per call in practice; the
        loop keeps the invariant rather than assuming it."""
        table = self._tables[seq_id]
        group = self._groups[seq_id]
        refs = self._refs[group]
        free = self._frees[group]
        start = self._lengths[seq_id] // self.cfg.page_size
        idxs = [i for i in range(start, len(table))
                if refs[table[i]] > 1]
        if len(idxs) > len(free):
            return None
        pairs = []
        for i in idxs:
            src = table[i]
            dst = free.pop()
            refs[src] -= 1
            refs[dst] = 1
            table[i] = dst
            pairs.append((src, dst))
        if pairs:
            # Our claim on any index entries ending at src moved with
            # the fork: keep registration honest by clamping what this
            # sequence counts as registered below the forked page.
            if self._registered.get(seq_id, 0) > idxs[0]:
                self._registered[seq_id] = idxs[0]
            self._emit("cow", seq_id)
        return pairs

    def register_prefix(self, seq_id, tokens) -> None:
        """Index every fully-committed page-aligned prefix of
        ``tokens`` (the sequence's token history) not yet registered.
        Keyed by the EXACT prefix bytes — matching is equality, not a
        lossy hash, so a hit can never alias two different prompts."""
        table = self._tables[seq_id]
        group = self._groups[seq_id]
        ps = self.cfg.page_size
        full = self._lengths[seq_id] // ps
        done = self._registered.get(seq_id, 0)
        if full <= done:
            return
        toks = np.array(tokens, np.int32)
        for j in range(done + 1, full + 1):
            key = toks[:j * ps].tobytes()
            self._index[group][key] = tuple(table[:j])
            self._page_keys[group].setdefault(
                table[j - 1], set()).add(key)
        self._registered[seq_id] = full

    def needs_register(self, seq_id) -> bool:
        """Does the sequence have committed pages not yet indexed?"""
        return (self._lengths[seq_id] // self.cfg.page_size
                > self._registered.get(seq_id, 0))

    def match_prefix(self, group: int, tokens):
        """Longest indexed page-aligned prefix of ``tokens`` resident
        in ``group``: returns ``(pages, n_pages)`` or ``((), 0)``."""
        index = self._index[group]
        if not index:
            return (), 0
        toks = np.array(tokens, np.int32)
        ps = self.cfg.page_size
        for j in range(len(toks) // ps, 0, -1):
            pages = index.get(toks[:j * ps].tobytes())
            if pages is not None:
                return pages, j
        return (), 0

    def _invalidate(self, group: int, page: int) -> None:
        """Drop the index entries whose last page just died."""
        for key in self._page_keys[group].pop(page, ()):
            self._index[group].pop(key, None)

    def shared_pages_in(self, group: int) -> int:
        """Pages in ``group`` held by more than one table."""
        return sum(1 for n in self._refs[group].values() if n > 1)

    def token_capacity(self, seq_id) -> int:
        """Max TOTAL positions this sequence could hold right now:
        its allocated pages plus everything left on its group's free
        list, capped by max_seq_len. The resident decode path sizes
        burst budgets against this so an in-program loop can never
        out-write what ``ensure`` could cover."""
        g = self._groups[seq_id]
        pages = len(self._tables[seq_id]) + len(self._frees[g])
        return min(pages * self.cfg.page_size, self.cfg.max_seq_len)

    def occupancy(self) -> dict:
        rec = {"pages_used": self.pages_used,
               "pages_total": self.cfg.usable_pages_total,
               "seqs": self.seqs}
        if self.cfg.dp_groups > 1:
            # Per-group occupancy rides the same record (additive —
            # the metrics observer folds these into the labeled
            # dtt_serving_* gauges; schema pinned by test).
            rec["group_pages_used"] = [
                self.pages_used_in(g)
                for g in range(self.cfg.dp_groups)]
            rec["group_seqs"] = [
                self.seqs_in(g) for g in range(self.cfg.dp_groups)]
        return rec

    # -- device-side views -------------------------------------------------

    def page_row(self, seq_id) -> np.ndarray:
        """(pages_per_seq,) int32 page-table row, scratch-padded."""
        row = np.zeros((self.cfg.pages_per_seq,), np.int32)
        table = self._tables[seq_id]
        row[:len(table)] = table
        return row

    def page_rows(self, seq_ids: list) -> np.ndarray:
        """(len(seq_ids), pages_per_seq) int32 table; ``None`` entries
        (empty batch slots) become all-scratch rows."""
        rows = np.zeros((len(seq_ids), self.cfg.pages_per_seq),
                        np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is not None:
                rows[i] = self.page_row(sid)
        return rows

    def page_rows_grouped(self, seq_ids_by_group: list,
                          width: int | None = None) -> np.ndarray:
        """(dp_groups, width, pages_per_seq) int32 tables from a
        per-group nested id list — the batched programs' layout
        (group g's rows index ONLY group g's pool shard). Lists may
        be RAGGED (the batched prefill packs however many lanes each
        group has pending): short groups pad with all-scratch rows up
        to ``width`` (default: the longest group's length — the
        decode path passes equal full-width lists)."""
        b = width if width is not None else max(
            (len(ids) for ids in seq_ids_by_group), default=0)
        rows = np.zeros((self.cfg.dp_groups, b,
                         self.cfg.pages_per_seq), np.int32)
        for g, ids in enumerate(seq_ids_by_group):
            for i, sid in enumerate(ids):
                if sid is not None:
                    rows[g, i] = self.page_row(sid)
        return rows

    def update_pools(self, k_pages, v_pages) -> None:
        """Adopt the jitted program's updated (donated-in) pools."""
        self.k_pages = k_pages
        self.v_pages = v_pages
