"""Paged KV cache: fixed-size pages in one preallocated, sharded pool.

Per-request max_len buffers waste HBM quadratically under continuous
batching (every slot reserves the worst case); the paged layout is
virtual memory for KV instead. One reservation of ``num_pages`` pages
of ``page_size`` tokens each, per layer, kv-head-major:

    k_pages, v_pages: (n_layers, n_kv_heads, num_pages, page_size,
                       head_dim)

A sequence owns an ordered list of physical page ids (its PAGE TABLE);
logical position ``p`` lives in slot ``p % page_size`` of its
``p // page_size``-th page. Join = allocate pages from the free list,
evict = return them — no copying, no compaction, and the device
arrays never change shape, so the decode program never recompiles.

**Page 0 is the scratch page**: never allocated, the write target for
inactive batch slots and padding positions (the jitted decode/prefill
programs write unconditionally; pointing dead writes at scratch keeps
them out of live pages without dynamic shapes). Unused page-table
entries also point at it — their slots are masked out of attention by
position, so the garbage is never read into a softmax.

**Sharding**: on a multi-device mesh the pool is sharded along the
kv-head axis over the plan's ``tp`` mesh axis (the decode plan's head
currency — serving's analogue of the training tp head shard), and
replicated elsewhere. Page tables/lengths are tiny int32 rows and stay
replicated.

**Accounting**: the allocator is host-side (plain Python — allocation
decisions are control flow, not math) and every alloc/free emits a
``serving_kv`` telemetry record with the pool occupancy, which the
metrics endpoint folds into ``dtt_serving_kv_pages_{used,total}``.
Invariant (pinned by test): ``pages_used + free == num_pages - 1``
always, and freeing every sequence returns occupancy to zero — the
pool cannot leak under any join/evict order.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from distributed_training_tpu.telemetry import event


@dataclass(frozen=True)
class PagedCacheConfig:
    """Pool geometry. ``max_seq_len`` bounds pages per sequence."""

    n_layers: int
    n_kv_heads: int
    head_dim: int
    page_size: int = 16
    num_pages: int = 128          # scratch page 0 included
    max_seq_len: int = 256
    dtype: str = "float32"

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got "
                             f"{self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is scratch), got "
                f"{self.num_pages}")
        if self.max_seq_len % self.page_size:
            raise ValueError(
                f"max_seq_len ({self.max_seq_len}) must be a multiple "
                f"of page_size ({self.page_size})")

    @property
    def pages_per_seq(self) -> int:
        return self.max_seq_len // self.page_size

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # minus scratch

    def kv_bytes_per_token(self) -> int:
        """HBM cost of one cached token across all layers (k + v)."""
        itemsize = np.dtype(self.dtype).itemsize
        return (2 * self.n_layers * self.n_kv_heads * self.head_dim
                * itemsize)


class PagedKVCache:
    """The pool + its host-side allocator and per-sequence tables.

    ``mesh``/``kv_axis``: shard the pools' kv-head dim over that mesh
    axis (skipped when the axis has extent 1 or no mesh is given).
    The device pools are handed to the engine's jitted programs as
    donated inputs; the engine writes the updated arrays back via
    ``update_pools`` each step.
    """

    def __init__(self, cfg: PagedCacheConfig, mesh=None,
                 kv_axis: str | None = None):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        shape = (cfg.n_layers, cfg.n_kv_heads, cfg.num_pages,
                 cfg.page_size, cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            ax = kv_axis if kv_axis and sizes.get(kv_axis, 1) > 1 \
                else None
            if ax is not None and cfg.n_kv_heads % sizes[ax]:
                raise ValueError(
                    f"kv pool cannot shard {cfg.n_kv_heads} kv heads "
                    f"over {kv_axis}={sizes[ax]}")
            sharding = NamedSharding(mesh, P(None, ax))
        self.sharding = sharding

        def pool():
            # Two DISTINCT buffers: k and v are donated separately to
            # the jitted programs, and donating one aliased array
            # twice is an XLA error.
            z = jnp.zeros(shape, dt)
            return jax.device_put(z, sharding) \
                if sharding is not None else z

        self.k_pages = pool()
        self.v_pages = pool()
        # Host allocator state. Free list is LIFO: recently-freed
        # pages are re-handed first (warm in cache, and deterministic
        # for the tests' join/evict permutations).
        self._free: list[int] = list(range(cfg.num_pages - 1, 0, -1))
        self._tables: dict[object, list[int]] = {}
        self._lengths: dict[object, int] = {}

    # -- allocator ---------------------------------------------------------

    @property
    def pages_used(self) -> int:
        return self.cfg.usable_pages - len(self._free)

    @property
    def seqs(self) -> int:
        return len(self._tables)

    def _emit(self, op: str, seq_id) -> None:
        event("serving_kv", op=op, seq=str(seq_id),
              pages_used=self.pages_used,
              pages_total=self.cfg.usable_pages, seqs=self.seqs)

    def can_admit(self, n_tokens: int) -> bool:
        """Would ``ensure`` succeed for a NEW sequence of n_tokens?"""
        need = -(-max(1, n_tokens) // self.cfg.page_size)
        return need <= len(self._free)

    def join(self, seq_id) -> None:
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already joined")
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0
        self._emit("join", seq_id)

    def ensure(self, seq_id, n_tokens: int) -> bool:
        """Grow seq_id's table to cover ``n_tokens`` total positions.
        Returns False (allocating NOTHING — admission is atomic per
        call) when the free list cannot cover the growth; the engine
        treats that as backpressure and defers the work."""
        if n_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"sequence {seq_id!r} needs {n_tokens} positions, "
                f"pool max_seq_len is {self.cfg.max_seq_len}")
        table = self._tables[seq_id]
        need = -(-n_tokens // self.cfg.page_size) - len(table)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(need):
            table.append(self._free.pop())
        self._emit("grow", seq_id)
        return True

    def advance(self, seq_id, n_tokens: int) -> None:
        """Record ``n_tokens`` more positions as written (pages must
        already be ensured)."""
        new_len = self._lengths[seq_id] + n_tokens
        table = self._tables[seq_id]
        if new_len > len(table) * self.cfg.page_size:
            raise RuntimeError(
                f"sequence {seq_id!r}: advancing to {new_len} "
                f"positions but only {len(table)} page(s) allocated "
                "— ensure() first")
        self._lengths[seq_id] = new_len

    def free(self, seq_id) -> int:
        """Evict: return the sequence's pages to the pool. Returns the
        page count released."""
        table = self._tables.pop(seq_id)
        del self._lengths[seq_id]
        self._free.extend(reversed(table))
        self._emit("free", seq_id)
        return len(table)

    def length(self, seq_id) -> int:
        return self._lengths[seq_id]

    def occupancy(self) -> dict:
        return {"pages_used": self.pages_used,
                "pages_total": self.cfg.usable_pages,
                "seqs": self.seqs}

    # -- device-side views -------------------------------------------------

    def page_row(self, seq_id) -> np.ndarray:
        """(pages_per_seq,) int32 page-table row, scratch-padded."""
        row = np.zeros((self.cfg.pages_per_seq,), np.int32)
        table = self._tables[seq_id]
        row[:len(table)] = table
        return row

    def page_rows(self, seq_ids: list) -> np.ndarray:
        """(len(seq_ids), pages_per_seq) int32 table; ``None`` entries
        (empty batch slots) become all-scratch rows."""
        rows = np.zeros((len(seq_ids), self.cfg.pages_per_seq),
                        np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is not None:
                rows[i] = self.page_row(sid)
        return rows

    def update_pools(self, k_pages, v_pages) -> None:
        """Adopt the jitted program's updated (donated-in) pools."""
        self.k_pages = k_pages
        self.v_pages = v_pages
