"""Prefill/decode disaggregation: two plans, one weight store.

Prefill is compute-bound (a prompt's worth of matmuls, batch-friendly)
and decode is latency-bound (one token per step, KV-residency-hungry)
— they want DIFFERENT layouts of the same weights. The planner
resolves both from one model (``parallel/planner.py`` objectives
"prefill"/"decode", committed as ``conf/plans/serving_4dev_cpu_*``),
and this module is everything that makes the pair runnable:

- ``WeightStore`` — the consolidated export artifact
  (checkpoint/export.py) loaded ONCE to host memory and laid out
  per-plan onto any mesh slice on demand. Plan provenance embedded in
  the artifact (the export CLI stamps the source run's plan name +
  fingerprint) is verified against the committed plan file: serving a
  checkpoint under a silently-regenerated plan is refused; legacy
  artifacts (no provenance) load with a warning.
- ``plan_shardings``/``place_params`` — a plan's sharding-map-by-name
  resolved to ``NamedSharding``s on a concrete mesh and applied with
  one ``device_put`` per leaf.
- ``DisaggPipeline`` — the end-to-end demo the parity test pins: the
  8-device mesh split into a prefill slice and a decode slice, each
  laid out under its own plan from the one store; prompts prefill on
  slice A, the paged KV hands off to slice B (dense per-sequence
  export → page-granular import, resharding kv-head layout in the
  copy), and continuous-batching decode finishes there. Greedy tokens
  are equal to the co-located engine's token-for-token.
- ``compile_verify_serving`` — the planner's stage-2 verifier for
  serving objectives: abstract-compile the engine's ACTUAL decode (or
  prefill) program under the candidate plan on a fake mesh and
  disqualify on any SPMD involuntary-reshard warning, exactly as
  ``compile_verify`` does for the train step.
"""

from __future__ import annotations

import logging

import numpy as np

from distributed_training_tpu.serving.engine import (
    Engine,
    EngineConfig,
    build_decode_fn,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Plan-directed placement
# ---------------------------------------------------------------------------


def _is_quant_leaf(x) -> bool:
    """An int8 weight-only leaf: ``{"qw": int8, "scale": f32}`` —
    the dict IS the leaf for placement purposes (one sharding entry
    in the plan covers both members)."""
    return isinstance(x, dict) and "qw" in x and "scale" in x


def plan_shardings(plan, mesh, params_tree):
    """Resolve ``plan.sharding_map`` (path → per-dim axis entries)
    into a pytree of NamedShardings matching ``params_tree``. Raises
    on a param path the plan does not name (same contract as
    PlannedStrategy: a model/plan mismatch fails at placement, not as
    a silently replicated layout).

    Int8 weight-only leaves (``{"qw", "scale"}`` dicts) resolve under
    the SAME committed entries as their fp32 original: ``qw`` keeps
    the weight's shape so it takes the plan's spec verbatim; the
    keepdims ``scale`` replicates every REDUCED (size-1) dim and
    inherits the spec on its kept output-channel dims — the quantized
    layout is the committed layout, not a new one."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def leaf(path, lf):
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                       for k in path)
        try:
            entries = plan.sharding_map[key]
        except KeyError:
            raise ValueError(
                f"plan '{plan.name}' names no sharding for param "
                f"'{key}' — it was resolved against a different "
                "model") from None

        def ns(ent):
            return NamedSharding(mesh, P(*[
                tuple(e) if isinstance(e, list) else e for e in ent]))

        if _is_quant_leaf(lf):
            scale_ent = [None if lf["scale"].shape[d] == 1 else e
                         for d, e in enumerate(entries)]
            return {"qw": ns(entries), "scale": ns(scale_ent)}
        return ns(entries)

    return jax.tree_util.tree_map_with_path(
        leaf, params_tree, is_leaf=_is_quant_leaf)


def place_params(params, mesh, plan):
    """One ``device_put`` per leaf onto the plan's layout."""
    import jax

    shardings = plan_shardings(plan, mesh, params)
    return jax.tree.map(jax.device_put, params, shardings)


# ---------------------------------------------------------------------------
# Int8 weight-only quantization
# ---------------------------------------------------------------------------

# The quantizable weight sites (the serving transformer's matmul
# operands) and the dims their per-OUTPUT-CHANNEL scale reduces over
# — dim 0 is the stacked layer axis, always kept. Embeddings, the LM
# head, norms and biases stay fp32: they are a rounding-error share
# of the bytes and the head's logits precision is the parity gate.
_QUANT_AXES: dict[tuple[str, str], tuple[int, ...]] = {
    ("attn", "wq"): (1,),        # (L, D, H, hd)  — reduce D
    ("attn", "wk"): (1,),        # (L, D, Hkv, hd)
    ("attn", "wv"): (1,),        # (L, D, Hkv, hd)
    ("attn", "wo"): (1, 2),      # (L, H, hd, D)  — reduce H, hd
    ("mlp", "wi"): (1,),         # (L, D, F)      — reduce D
    ("mlp", "wo"): (1,),         # (L, F, D)      — reduce F
}


def _quantize_leaf(w, axes: tuple[int, ...]) -> dict:
    """Symmetric per-channel int8: ``qw * scale ≈ w`` with one f32
    scale per output channel (keepdims — broadcast at dequant). An
    all-zero channel keeps scale 1.0 (qw is 0 there anyway)."""
    w = np.array(w, np.float32)
    amax = np.max(np.abs(w), axis=axes, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    qw = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"qw": qw, "scale": scale}


def quantize_params_int8(params):
    """The int8 weight-only layout of a serving params tree: every
    ``_QUANT_AXES`` site becomes a ``{"qw": int8, "scale": f32}``
    leaf (4× the bytes of the dominant weights back); everything
    else passes through untouched. The engine's programs dequantize
    AT COMPUTE through one helper (serving/engine.py ``_w``), so
    fp32 and int8 stores run the same program bodies."""
    out = dict(params)
    for (grp, name), axes in _QUANT_AXES.items():
        if grp not in out or name not in out[grp]:
            continue
        sub = dict(out[grp])
        sub[name] = _quantize_leaf(sub[name], axes)
        out[grp] = sub
    return out


def quantized_weight_bytes(params) -> dict:
    """``{"fp32": bytes, "int8": bytes}`` for a (possibly already
    quantized) params tree — the planner's HBM credit and the bench's
    ``weight_bytes`` evidence share this arithmetic."""
    import jax

    fp32 = int8 = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=_is_quant_leaf):
        if _is_quant_leaf(leaf):
            fp32 += 4 * int(np.prod(leaf["qw"].shape))
            int8 += (leaf["qw"].size * leaf["qw"].dtype.itemsize
                     + leaf["scale"].size
                     * leaf["scale"].dtype.itemsize)
        else:
            n = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            fp32 += n
            int8 += n
    return {"fp32": fp32, "int8": int8}


# ---------------------------------------------------------------------------
# The weight store
# ---------------------------------------------------------------------------


class ProvenanceError(ValueError):
    """Artifact plan provenance contradicts the committed plan."""


class WeightStore:
    """One consolidated artifact, many per-plan layouts.

    Loads the msgpack export (host NumPy — no mesh needed) exactly
    once; ``params_for(mesh, plan)`` lays the SAME host copy out under
    any plan on any mesh slice, which is what lets prefill and decode
    slices share a checkpoint without double-loading or re-export.

    Provenance contract (checkpoint/export.py stamps it): an artifact
    carrying ``meta["sharding_plan"] = {"name", "fingerprint"}``
    refuses to load when the committed plan of that name now has a
    DIFFERENT fingerprint — weights exported under one resolved
    layout must not silently serve under a regenerated one (re-export
    or re-plan deliberately instead). Artifacts without the stamp
    (legacy / foreign) load with a warning.
    """

    def __init__(self, artifact_path: str, check_provenance: bool = True):
        from distributed_training_tpu.checkpoint.consolidate import (
            load_consolidated)

        state, meta = load_consolidated(artifact_path)
        self.path = artifact_path
        self.meta = meta
        self.state = state
        self.params = state["params"] if "params" in state else state
        # Quantization provenance: the export CLI stamps the layout
        # it wrote (checkpoint/export.py --quantize); an unknown
        # stamp is refused rather than served as garbage weights.
        self.quantization = str(
            (meta or {}).get("quantization", "none"))
        if self.quantization not in ("none", "int8"):
            raise ValueError(
                f"artifact {artifact_path} stamps unknown "
                f"quantization '{self.quantization}' (supported: "
                "none, int8)")
        if check_provenance:
            self._check_provenance()

    def _check_provenance(self) -> None:
        from distributed_training_tpu.parallel.planner import (
            PlanError, load_plan)

        prov = self.meta.get("sharding_plan")
        if not prov:
            logger.warning(
                "artifact %s carries no sharding-plan provenance "
                "(legacy or foreign export) — serving layout cannot "
                "be cross-checked against the training plan",
                self.path)
            return
        name = prov.get("name")
        try:
            committed = load_plan(name)
        except (PlanError, FileNotFoundError) as e:
            raise ProvenanceError(
                f"artifact {self.path} was exported from plan "
                f"'{name}', which no longer loads ({e}) — re-export "
                "from a run on a committed plan") from e
        if committed.fingerprint() != prov.get("fingerprint"):
            raise ProvenanceError(
                f"artifact {self.path} was exported from plan "
                f"'{name}'@{prov.get('fingerprint')}, but the "
                f"committed plan is now @{committed.fingerprint()} — "
                "the plan was regenerated since export; re-export "
                "the checkpoint (or restore the plan) rather than "
                "serving weights under a layout that does not match "
                "their provenance")

    @property
    def provenance(self) -> dict | None:
        """The artifact's plan provenance stamp ``{"name",
        "fingerprint"}`` (None on legacy artifacts) — the baseline
        ``Engine.swap_weights`` gates every live publish against."""
        prov = (self.meta or {}).get("sharding_plan")
        return dict(prov) if prov else None

    def params_for(self, mesh, plan):
        """The host weights laid out under ``plan`` on ``mesh``."""
        import jax.numpy as jnp
        import jax

        params = jax.tree.map(jnp.asarray, self.params)
        return place_params(params, mesh, plan)


# ---------------------------------------------------------------------------
# KV handoff between slices
# ---------------------------------------------------------------------------


def export_kv(cache, seq_id):
    """A sequence's KV as dense host arrays (L, Hkv, len, hd) —
    page-table indirection resolved, ready to cross a mesh boundary
    (the handoff wire format; at pod scale this is the DCN payload)."""
    k, v = export_kv_batch(cache, [seq_id])
    return k[0], v[0]


def export_kv_batch(cache, seq_ids):
    """Dense KV for MANY in-flight sequences in ONE device→host
    transfer — the continuous-handoff rate path: the page gather for
    every sequence in the batch is a single device slice instead of
    one transfer per request (per-request ``export_kv`` is this with
    a batch of one, so the two can never produce different bytes).
    Returns ``(ks, vs)`` — parallel lists of (L, Hkv, len_i, hd)
    arrays."""
    pages_of, lens = [], []
    for sid in seq_ids:
        n = cache.length(sid)
        n_pages = -(-n // cache.cfg.page_size) if n else 0
        pages_of.append((cache.group_of(sid),
                         cache.page_row(sid)[:n_pages]))
        lens.append(n)
    if not seq_ids:
        return [], []
    # One gather over the union of (group, page) coordinates, sliced
    # ON DEVICE before pulling to host: np.asarray(pool) would
    # materialize the ENTIRE pool; this transfers only the batch's
    # own pages, once.
    groups = np.concatenate([np.full(len(p), g, np.int32)
                             for g, p in pages_of]) \
        if any(len(p) for _g, p in pages_of) else np.zeros(0, np.int32)
    pages = np.concatenate([p for _g, p in pages_of]) \
        if groups.size else np.zeros(0, np.int32)
    k_all = np.asarray(cache.k_pages[groups, :, :, pages])
    v_all = np.asarray(cache.v_pages[groups, :, :, pages])
    ks, vs = [], []
    off = 0
    ps = cache.cfg.page_size
    for (_g, p), n in zip(pages_of, lens):
        kseq = k_all[off:off + len(p)]        # (p, L, Hkv, ps, hd)
        vseq = v_all[off:off + len(p)]
        off += len(p)
        L = cache.cfg.n_layers
        Hkv = cache.cfg.n_kv_heads
        hd = cache.cfg.head_dim
        k = kseq.transpose(1, 2, 0, 3, 4).reshape(
            L, Hkv, len(p) * ps, hd)[:, :, :n]
        v = vseq.transpose(1, 2, 0, 3, 4).reshape(
            L, Hkv, len(p) * ps, hd)[:, :, :n]
        ks.append(k)
        vs.append(v)
    return ks, vs


def import_kv(cache, seq_id, k, v) -> None:
    """Write dense (L, Hkv, len, hd) KV into a (different) cache's
    pages for ``seq_id`` (already joined; pages are ensured here —
    in the sequence's own dp group's shard). The destination pool's
    sharding resharding happens in the ``.at[].set`` device_puts —
    kv-head/group layout follows the destination mesh."""
    import_kv_batch(cache, [(seq_id, k, v)])


def import_kv_batch(cache, items) -> None:
    """Batched page-granular import: ``items`` is a list of
    ``(seq_id, k, v)`` dense KV triples (every seq already joined).
    All pages across all sequences land in ONE scatter per pool —
    the per-engine-step transfer the continuous handoff batches,
    instead of one device round-trip per request. Raises when a
    destination group's shard cannot hold a sequence, and the raise
    aborts the WHOLE batch before the scatter: nothing is written
    and no cursor advances, but earlier items' pages are left
    allocated-and-empty (ensure() is atomic per sequence). Callers
    must free every item and retry — ``Engine.adopt_batch`` does."""
    todo = []
    ps = cache.cfg.page_size
    for seq_id, k, v in items:
        n = k.shape[2]
        if n == 0:
            continue
        if not cache.ensure(seq_id, n):
            raise RuntimeError(
                f"KV import for {seq_id!r}: destination pool cannot "
                f"hold {n} positions")
        todo.append((seq_id, k, v, n))
    if not todo:
        return
    groups, pages, k_chunks, v_chunks = [], [], [], []
    for seq_id, k, v, n in todo:
        g = cache.group_of(seq_id)
        table = cache._tables[seq_id]
        for j, pid in enumerate(table[: -(-n // ps)]):
            lo, hi = j * ps, min((j + 1) * ps, n)
            kc = np.zeros((k.shape[0], k.shape[1], ps, k.shape[3]),
                          k.dtype)
            vc = kc.copy()
            kc[:, :, :hi - lo] = k[:, :, lo:hi]
            vc[:, :, :hi - lo] = v[:, :, lo:hi]
            groups.append(g)
            pages.append(pid)
            k_chunks.append(kc)
            v_chunks.append(vc)
    gi = np.asarray(groups, np.int32)
    pi = np.asarray(pages, np.int32)
    kp = cache.k_pages.at[gi, :, :, pi].set(np.stack(k_chunks))
    vp = cache.v_pages.at[gi, :, :, pi].set(np.stack(v_chunks))
    cache.update_pools(kp, vp)
    for seq_id, _k, _v, n in todo:
        cache.advance(seq_id, n)


# ---------------------------------------------------------------------------
# The disaggregated pipeline
# ---------------------------------------------------------------------------


def engine_config_for_plan(plan, page_size: int = 16,
                           prefill_chunk: int = 16,
                           prefill_mode: str = "batched",
                           spec_k: int = 1,
                           resident_k: int = 1) -> EngineConfig:
    """The ONE engine geometry a plan implies — shared by the bench,
    the disagg pipeline, and the analysis audit targets so they all
    compile the same program shapes. ``batch_per_shard`` is the
    AGGREGATE slot count, dealt over the plan's ``dp`` groups
    (serving/engine.py) — decode slots for decode plans, prefill
    lanes for prefill plans (``prefill_slots`` defaults to the same
    table); ``num_pages`` is each group's pool shard, sized so its
    own slots fit at full length — the whole-pool total is the same
    HBM the replicated-table engine reserved, now batch-sharded.
    ``prefill_mode``/``spec_k`` select the batched-prefill and
    speculative-decode programs (SERVING_r03); the plan's layout is
    program-agnostic — dp deals lanes, tp shards heads, either way.

    Pool sizing (SERVING_r05): when the plan's provenance carries
    ``kv_pool_tokens`` (the planner's residual-HBM-credit sizing —
    int8 plans vacate weight bytes that become KV pages), each
    group's shard is grown to hold its share of that token budget;
    plans without the field keep the minimal slots-fit-at-full-length
    pool, so pre-r05 plan files stay valid."""
    slots = plan.batch_per_shard
    dp = plan.mesh.get("dp", 1)
    if slots % dp:
        raise ValueError(
            f"plan '{plan.name}': batch_per_shard ({slots}) does not "
            f"deal over dp={dp} — the planner must not emit this "
            "(slots%dp feasibility)")
    pages_per_seq = -(-plan.seq_len // page_size)
    num_pages = (slots // dp) * pages_per_seq + 1
    pool_tokens = ((plan.provenance or {}).get("score") or {}).get(
        "kv_pool_tokens")
    if isinstance(pool_tokens, int) and pool_tokens > 0:
        num_pages = max(num_pages,
                        -(-(pool_tokens // dp) // page_size) + 1)
    return EngineConfig(
        max_batch=slots,
        page_size=page_size,
        num_pages=num_pages,
        max_seq_len=plan.seq_len,
        prefill_chunk=prefill_chunk,
        prefill_mode=prefill_mode,
        spec_k=spec_k,
        resident_k=resident_k,
        kv_axis="tp",
        dp_axis="dp")


class DisaggPipeline:
    """Prefill on one mesh slice, decode on another, one WeightStore.

    ``prefill_devices``/``decode_devices``: disjoint device lists
    (the 4+4 split of the 8-device CPU mesh in tests). Each slice
    builds its own mesh from its plan's axes and lays the shared
    weights out under that plan. ``generate`` runs the full path:
    chunked prefill on slice A, dense-KV handoff, continuous-batching
    decode on slice B.
    """

    def __init__(self, store: WeightStore, prefill_plan, decode_plan,
                 prefill_devices, decode_devices,
                 page_size: int = 16, prefill_chunk: int = 16):
        from distributed_training_tpu.parallel.planner import (
            model_for_plan, model_kwargs_for)
        from distributed_training_tpu.runtime import MeshSpec, build_mesh

        mk_p = model_kwargs_for(prefill_plan)
        mk_d = model_kwargs_for(decode_plan)
        if {k: v for k, v in mk_p.items() if k != "remat"} != \
                {k: v for k, v in mk_d.items() if k != "remat"}:
            raise ValueError(
                "prefill and decode plans describe different models "
                "— disaggregation requires one model, two layouts")
        self.model = model_for_plan(decode_plan)

        def slice_mesh(plan, devices):
            spec = MeshSpec(**{a: plan.mesh.get(a, 1)
                               for a in ("pp", "dp", "fsdp", "sp",
                                         "tp")})
            if spec.total != len(devices):
                raise ValueError(
                    f"plan '{plan.name}' needs {spec.total} devices, "
                    f"slice has {len(devices)}")
            return build_mesh(spec, list(devices))

        self.prefill_mesh = slice_mesh(prefill_plan, prefill_devices)
        self.decode_mesh = slice_mesh(decode_plan, decode_devices)
        self.prefill_params = store.params_for(self.prefill_mesh,
                                               prefill_plan)
        # Prefill slice: an Engine used only for its prefill programs
        # + pool (its decode program never runs).
        self.prefill_engine = Engine(
            self.model, self.prefill_params,
            engine_config_for_plan(prefill_plan, page_size,
                                   prefill_chunk),
            mesh=self.prefill_mesh)
        self.decode_engine = Engine(
            self.model, store.params_for(self.decode_mesh,
                                         decode_plan),
            engine_config_for_plan(decode_plan, page_size,
                                   prefill_chunk),
            mesh=self.decode_mesh)

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 req_id: str = "disagg",
                 tenant: str = "default") -> list[int]:
        from distributed_training_tpu.serving.engine import Request

        prompt = np.array(prompt, np.int32)
        pe = self.prefill_engine
        req = Request(id=req_id, prompt=prompt,
                      max_new_tokens=max_new_tokens, tenant=tenant)
        pe.submit(req)
        # Drive ONLY prefill steps on the prefill slice: the request
        # completes its prompt and samples the first token there.
        while not any(s is not None and s.prefill_done
                      for s in pe.slots):
            rec = pe.step()
            if rec["op"] == "idle":
                raise RuntimeError("prefill slice made no progress")
        seq = next(s for s in pe.slots
                   if s is not None and s.prefill_done)
        first_token = seq.generated[0]
        k, v = export_kv(pe.cache, req.id)
        # Release the prefill slice (continuous batching: the slot is
        # immediately reusable for the next prompt).
        pe.cache.free(req.id)
        pe.slots[seq.slot] = None
        de = self.decode_engine
        # The adopted Request keeps the ORIGINAL arrival and tenant:
        # the decode-side trace must account the whole journey
        # (prefill slice included) to the submitting tenant.
        de.adopt(Request(id=req_id, prompt=prompt,
                         max_new_tokens=max_new_tokens,
                         arrival=req.arrival, tenant=tenant),
                 first_token, k, v)
        de.run_until_drained()
        rec = next(r for r in reversed(de.completed)
                   if r["id"] == req_id)
        return rec["tokens"]

    def generate_many(self, requests, max_steps: int = 100_000
                      ) -> dict:
        """CONTINUOUS KV handoff at rate: drive many requests through
        the pair with page transfers batched per engine step and
        overlapped with ongoing decode, instead of one synchronous
        transfer per request (``generate``'s shape).

        Per loop iteration: the prefill slice takes one step (its own
        continuous batch of prompts); every sequence that finished
        its prompt THIS step is exported in ONE batched device→host
        gather, adopted into the decode slice in ONE batched scatter
        (``export_kv_batch``/``import_kv_batch``), and the decode
        slice takes one step for everything already adopted — so
        handoffs for late prompts ride alongside decode for early
        ones. A handoff the decode slice cannot absorb yet
        (slots/pages) is held and retried next iteration —
        backpressure, not failure.

        ``requests`` is a list of Requests; returns
        ``{req_id: tokens}``, token-identical to the per-request path
        (pinned by test)."""
        pe, de = self.prefill_engine, self.decode_engine
        for r in requests:
            pe.submit(r)
        want = {r.id for r in requests}
        held: list = []       # handoffs awaiting decode capacity
        for _ in range(max_steps):
            done = {r["id"]: r["tokens"] for r in de.completed}
            # Finished-on-prefill requests (<= chunk prompts whose
            # first token IS the last token) complete on pe.
            done.update({r["id"]: r["tokens"] for r in pe.completed
                         if r["id"] in want})
            if want <= set(done):
                return {rid: done[rid] for rid in want}
            if not pe.idle:
                pe.step()
            # Collect every sequence that completed its prompt —
            # batch their exports into one transfer.
            ready = [s for s in pe.slots
                     if s is not None and s.prefill_done]
            if ready:
                ids = [s.req.id for s in ready]
                ks, vs = export_kv_batch(pe.cache, ids)
                for s, k, v in zip(ready, ks, vs):
                    held.append((s.req, s.generated[0], k, v))
                    pe.cache.free(s.req.id)
                    pe.slots[s.slot] = None
            if held:
                try:
                    de.adopt_batch(held)
                    held = []
                except RuntimeError:
                    # Decode slice cannot take the WHOLE batch
                    # (adopt_batch is all-or-nothing): adopt whatever
                    # fits one-by-one, hold the rest for the next
                    # iteration — backpressure must make partial
                    # progress or a burst larger than the decode
                    # table would livelock.
                    still = []
                    for item in held:
                        try:
                            de.adopt_batch([item])
                        except RuntimeError:
                            still.append(item)
                    held = still
            if not de.idle:
                de.step()
        raise RuntimeError(
            f"disagg pipeline not drained after {max_steps} steps "
            f"({len(held)} handoff(s) held, prefill idle={pe.idle}, "
            f"decode idle={de.idle})")


# ---------------------------------------------------------------------------
# Stage-2 verifier for serving-objective plans
# ---------------------------------------------------------------------------


def _quantize_struct(params_shapes):
    """The int8 layout's ShapeDtypeStruct tree — the abstract twin of
    ``quantize_params_int8`` (same sites, same keepdims scale shapes)
    so plan verification compiles the program quantized stores
    actually run."""
    import jax
    import jax.numpy as jnp

    out = dict(params_shapes)
    for (grp, name), axes in _QUANT_AXES.items():
        if grp not in out or name not in out[grp]:
            continue
        sub = dict(out[grp])
        s = sub[name]
        sshape = tuple(1 if d in axes else n
                       for d, n in enumerate(s.shape))
        sub[name] = {
            "qw": jax.ShapeDtypeStruct(s.shape, jnp.int8),
            "scale": jax.ShapeDtypeStruct(sshape, jnp.float32)}
        out[grp] = sub
    return out


def lower_serving_program(plan, objective: str):
    """Abstractly lower the engine's compiled program for ``plan``
    (objective "decode" → the dp-sharded group-batched decode
    program; "prefill" → the BATCHED multi-sequence prefill program,
    the served path since SERVING_r03; "resident" → the
    DEVICE-RESIDENT K-step decode loop, SERVING_r04's served decode
    path) on a fake CPU mesh with params laid out per the plan.
    Returns ``(lowered, mesh)`` — no state materialized
    (ShapeDtypeStruct inputs carrying the plan's NamedShardings,
    analysis/compile.py's discipline). The program itself comes from
    the SAME builders the engine compiles (serving/engine.py
    ``build_decode_fn``/``build_prefill_batch_fn``/
    ``build_resident_decode_fn``), so the verified program and the
    served program can never drift — shard_map over dp included. A
    plan carrying ``inputs["quant"] == "int8"`` lowers against the
    quantized param structs, so the dequant-at-compute einsums are
    in the verified HLO."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_training_tpu.parallel.planner import (
        model_for_plan)
    from distributed_training_tpu.runtime import fake_cpu_runtime
    from distributed_training_tpu.serving.engine import (
        build_prefill_batch_fn, build_resident_decode_fn)

    jax.config.update("jax_platforms", "cpu")
    model = model_for_plan(plan)
    rt = fake_cpu_runtime(plan.devices,
                          **{a: s for a, s in plan.mesh.items()
                             if s > 1})
    mesh = rt.mesh
    resident = objective == "resident"
    ecfg = dataclasses.replace(
        engine_config_for_plan(
            plan, spec_k=4 if resident else 1,
            resident_k=4 if resident else 1),
        paged_impl="ref")
    c = model.cfg
    params_shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    if plan.inputs.get("quant", "none") == "int8":
        params_shapes = _quantize_struct(params_shapes)
    shardings = plan_shardings(plan, mesh, params_shapes)
    params = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=sh),
        params_shapes, shardings)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    kv_ax = "tp" if sizes.get("tp", 1) > 1 else None
    dp_ax = "dp" if sizes.get("dp", 1) > 1 else None
    G = sizes.get("dp", 1)
    B = ecfg.max_batch // G
    pool_shard = NamedSharding(mesh, P(dp_ax, None, kv_ax))
    pool = jax.ShapeDtypeStruct(
        (G, c.n_layers, c.n_kv_heads, ecfg.num_pages, ecfg.page_size,
         c.head_dim), jnp.dtype(c.dtype), sharding=pool_shard)
    rep = NamedSharding(mesh, P())
    grp = NamedSharding(mesh, P(dp_ax))
    Ppages = -(-ecfg.max_seq_len // ecfg.page_size)

    def arr(shape, dtype, sh=rep):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    if objective == "decode":
        fn = build_decode_fn(c, ecfg, mesh=mesh)
        args = (params, pool, pool, arr((G, B), jnp.int32, grp),
                arr((G, B), jnp.int32, grp),
                arr((G, B, Ppages), jnp.int32, grp),
                arr((G, B), jnp.bool_, grp),
                arr((G, 2), jnp.uint32, grp))
    elif objective == "resident":
        # The device-resident burst program at the r04 bench shape
        # (resident_k=4, spec_k=4) — page rows, history, cursors and
        # stop flags all group-batched; no rng (greedy by contract).
        fn = build_resident_decode_fn(c, ecfg, mesh=mesh)
        args = (params, pool, pool,
                arr((G, B, Ppages), jnp.int32, grp),
                arr((G, B, ecfg.max_seq_len), jnp.int32, grp),
                arr((G, B), jnp.int32, grp),
                arr((G, B), jnp.int32, grp),
                arr((G, B), jnp.bool_, grp))
    else:
        # The batched prefill lane table: the plan's slot count dealt
        # over dp, prefill_chunk tokens per lane — exactly the
        # program Engine._run_prefill_batch launches.
        fn = build_prefill_batch_fn(c, ecfg, mesh=mesh)
        Sp = (ecfg.prefill_slots or ecfg.max_batch) // G
        C = ecfg.prefill_chunk
        args = (params, pool, pool,
                arr((G, Sp, Ppages), jnp.int32, grp),
                arr((G, Sp, C), jnp.int32, grp),
                arr((G, Sp), jnp.int32, grp),
                arr((G, Sp), jnp.int32, grp),
                arr((G, Sp), jnp.bool_, grp),
                arr((G, 2), jnp.uint32, grp))
    return fn.lower(*args), mesh


def compile_serving_hlo(plan, objective: str):
    """Compile the lowered serving program, capturing the SPMD
    partitioner's stderr. Returns ``(hlo_text, reshard_warnings,
    mesh)`` — the raw material for both the planner's disqualify
    decision and the audit target's findings."""
    from distributed_training_tpu.telemetry import collectives

    lowered, mesh = lower_serving_program(plan, objective)
    with collectives.capture_stderr_fd() as cap:
        text = lowered.compile().as_text()
    return text, collectives.parse_reshard_warnings(cap.text), mesh


def compile_verify_serving(target, plan) -> dict:
    """The planner's stage-2 verifier for serving-objective targets:
    same evidence dict shape as planner.compile_verify — any reshard
    warning disqualifies the candidate either way."""
    from distributed_training_tpu.telemetry import collectives

    text, warnings, mesh = compile_serving_hlo(plan,
                                               target.objective)
    coll = collectives.audit_hlo_text(text, mesh=mesh)
    return {
        "spmd_reshard_warnings": len(warnings),
        "reshard_ops": sorted({w["op"] for w in warnings}),
        "collective_bytes_per_step": coll["bytes_per_step"],
        "total_collectives": coll["total_collectives"],
        "program": {"decode": "decode",
                    "resident": "resident"}.get(target.objective,
                                                "prefill_batch"),
    }
