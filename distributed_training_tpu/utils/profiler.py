"""Tracing / profiling subsystem.

The reference has none — no torch profiler, no NVTX, no TensorBoard
(SURVEY.md §5.1); the closest artifact is log timestamps
(src/distributed_trainer.py:221-224). On TPU the platform profiler is
``jax.profiler``: traces capture XLA op timelines, HBM usage, and ICI
collective activity, viewable in TensorBoard/Perfetto/XProf. This module
wraps it with the two idioms a trainer needs — a bounded step-window
trace and an on-demand trace server — plus annotation helpers.
"""

from __future__ import annotations

import contextlib
import logging
import os

import jax

logger = logging.getLogger(__name__)


def start_server(port: int = 9999) -> None:
    """Expose the live profiler (``jax.profiler.start_server``) so
    TensorBoard / XProf can capture a trace from a running job on
    demand — the production idiom for multi-host pods (capture on any
    worker while training runs)."""
    jax.profiler.start_server(port)
    logger.info("profiler server listening on port %d", port)


@contextlib.contextmanager
def trace(logdir: str, host_only_on_coordinator: bool = False,
          process_index: int = 0):
    """Trace everything inside the block to ``logdir``.

    On multi-host runs every process traces its own devices; pass
    ``host_only_on_coordinator=True`` to trace just process 0 (smaller
    artifacts, usually enough to diagnose a step)."""
    if host_only_on_coordinator and process_index != 0:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named region in the trace timeline (host + device)."""
    return jax.profiler.TraceAnnotation(name)


def trace_steps(trainer, batches, logdir: str, warmup: int = 2) -> int:
    """Profile a short step window: run ``warmup`` steps uncaptured
    (compile + cache), then trace the remaining batches. Returns the
    number of traced steps."""
    it = iter(batches)
    done = 0
    for _ in range(warmup):
        try:
            trainer.train_step(next(it))
        except StopIteration:
            break
    with trace(logdir):
        for batch in it:
            metrics = trainer.train_step(batch)
            done += 1
        if done:
            jax.block_until_ready(metrics["loss"])
    return done
