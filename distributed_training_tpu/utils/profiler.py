"""Tracing / profiling subsystem.

The reference has none — no torch profiler, no NVTX, no TensorBoard
(SURVEY.md §5.1); the closest artifact is log timestamps
(src/distributed_trainer.py:221-224). On TPU the platform profiler is
``jax.profiler``: traces capture XLA op timelines, HBM usage, and ICI
collective activity, viewable in TensorBoard/Perfetto/XProf. This module
wraps it with the two idioms a trainer needs — a bounded step-window
trace and an on-demand trace server — plus annotation helpers.
"""

from __future__ import annotations

import contextlib
import logging
import os
from dataclasses import dataclass

import jax

logger = logging.getLogger(__name__)

# Live profiler-server singleton: jax.profiler.start_server raises on
# a second call (the port is held), so the server handle is process
# state and start/stop must be idempotent — multiple subsystems
# (trainer, bench, an operator's REPL) may each "ensure" the server.
_SERVER = None
_SERVER_PORT: int | None = None


def start_server(port: int = 9999):
    """Expose the live profiler (``jax.profiler.start_server``) so
    TensorBoard / XProf can capture a trace from a running job on
    demand — the production idiom for multi-host pods (capture on any
    worker while training runs). Idempotent: a second call returns
    the running server (a port mismatch is logged — the first server
    keeps its port)."""
    global _SERVER, _SERVER_PORT
    if _SERVER is not None:
        if port != _SERVER_PORT:
            logger.warning(
                "profiler server already on port %d; ignoring "
                "request for port %d", _SERVER_PORT, port)
        return _SERVER
    _SERVER = jax.profiler.start_server(port)
    _SERVER_PORT = port
    logger.info("profiler server listening on port %d", port)
    return _SERVER


def stop_server() -> None:
    """Stop the live profiler server if running (idempotent)."""
    global _SERVER, _SERVER_PORT
    if _SERVER is None:
        return
    jax.profiler.stop_server()
    _SERVER = None
    _SERVER_PORT = None
    logger.info("profiler server stopped")


@contextlib.contextmanager
def trace(logdir: str, host_only_on_coordinator: bool = False,
          process_index: int = 0):
    """Trace everything inside the block to ``logdir``.

    On multi-host runs every process traces its own devices; pass
    ``host_only_on_coordinator=True`` to trace just process 0 (smaller
    artifacts, usually enough to diagnose a step)."""
    if host_only_on_coordinator and process_index != 0:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named region in the trace timeline (host + device)."""
    return jax.profiler.TraceAnnotation(name)


@dataclass(frozen=True)
class TraceResult:
    """What a bounded trace produced: how many steps were captured and
    where the artifact tree landed (callers log/store the path — the
    trace is evidence, not a side effect)."""

    steps: int
    logdir: str


def trace_steps(trainer, batches, logdir: str,
                warmup: int = 2) -> TraceResult:
    """Profile a short step window: run ``warmup`` steps uncaptured
    (compile + cache), then trace the remaining batches."""
    it = iter(batches)
    done = 0
    for _ in range(warmup):
        try:
            trainer.train_step(next(it))
        except StopIteration:
            break
    with trace(logdir):
        for batch in it:
            metrics = trainer.train_step(batch)
            done += 1
        if done:
            jax.block_until_ready(metrics["loss"])
    return TraceResult(steps=done, logdir=logdir)
