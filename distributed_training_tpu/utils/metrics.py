"""Metrics: step timing, throughput, MFU accounting.

The reference logs only epoch boundaries and batch counts
(src/distributed_trainer.py:169-173); its README's performance guides are
an unfulfilled roadmap item (README.md:198). The BASELINE.json metric —
samples/sec/chip + MFU — requires real instrumentation, so this module is
a first-class subsystem (SURVEY.md §5.1/§5.5).
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


def sanitize_for_json(value):
    """Map non-finite floats to null, recursively through dicts/lists
    — bare NaN/Infinity are not valid JSON and break strict consumers
    (jq, JSON.parse). Shared by the metrics and telemetry jsonl
    writers so the two streams stay parseable by the same tools."""
    if isinstance(value, float) and not math.isfinite(value):
        return None
    if isinstance(value, dict):
        return {k: sanitize_for_json(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [sanitize_for_json(v) for v in value]
    return value

# Peak dense bf16 FLOPs per chip. Sources: public TPU spec sheets.
TPU_PEAK_FLOPS: dict[str, float] = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e11,  # nominal, keeps MFU finite in tests
}


def peak_flops_per_chip(device_kind: str) -> float:
    kind = device_kind.lower()
    for key, flops in TPU_PEAK_FLOPS.items():
        if key in kind:
            return flops
    return TPU_PEAK_FLOPS["cpu"]


def compute_mfu(model_flops_per_sec_per_chip: float,
                device_kind: str) -> float:
    return model_flops_per_sec_per_chip / peak_flops_per_chip(device_kind)


@dataclass
class MetricsLogger:
    """Rolling per-step throughput/loss logging on the coordinator.

    ``jsonl_path`` (optional) appends every recorded entry as one JSON
    line — the durable metrics stream (loss curves, samples/sec/chip,
    MFU, val_loss) that BASELINE.json's measurement protocol calls for;
    the reference has only transient log lines (SURVEY.md §5.5).
    ``jsonl_fresh=True`` truncates the file at the first write (a
    from-scratch run in a reused run_dir must not interleave with the
    previous run's rows); resumed runs append, separated by a
    ``run_start`` marker line carrying the resume step.

    The first recorded row is flagged ``"warmup": true`` and carries
    no throughput numbers: the interval from construction to the
    first record is jit-compile dominated, so the steps/sec window
    opens at the first row and the second row is the first clean
    throughput measurement."""

    log_every: int = 10
    samples_per_step: int = 0
    flops_per_sample: float = 0.0
    num_devices: int = 1
    enabled: bool = True
    device_kind: str = "cpu"
    jsonl_path: str | None = None
    jsonl_fresh: bool = True
    start_step: int = 0
    # Optional callback invoked with every appended entry dict. The
    # entry is already fully host-side (the loss float above is the
    # one device sync, and it happens regardless) — the trainer wires
    # this to re-emit entries as ``train_metrics`` telemetry events so
    # the anomaly detector sees loss/throughput with ZERO new syncs.
    # Exceptions are swallowed: a consumer must not break logging.
    on_entry: object = None

    # None until the first record(): the throughput window starts at
    # the first recorded row, NOT at construction — the gap between
    # them is jit compile time, which used to fold into the first
    # row's steps_per_sec and silently understate throughput.
    _last_time: float | None = field(default=None)
    _last_step: int = 0
    history: list[dict] = field(default_factory=list)

    def __post_init__(self) -> None:
        # Resume: the throughput window must start at the resume step,
        # or the first row computes dsteps from 0 and reports a
        # ~(start_step/log_every)x inflated rate into the ledger.
        self._last_step = self.start_step
        if self.jsonl_path and self.enabled:
            # Eager open: a fresh run must truncate a reused run_dir's
            # previous stream even if it crashes before the first
            # recorded entry (stale curves misattribute silently).
            import json
            import os
            os.makedirs(os.path.dirname(self.jsonl_path) or ".",
                        exist_ok=True)
            mode = "w" if self.jsonl_fresh else "a"
            with open(self.jsonl_path, mode) as f:
                f.write(json.dumps(
                    {"run_start": True,
                     "step": self.start_step}) + "\n")

    def _append(self, entry: dict) -> None:
        self.history.append(entry)
        if self.jsonl_path:
            import json
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(sanitize_for_json(entry),
                                   allow_nan=False) + "\n")
        if self.on_entry is not None:
            try:
                self.on_entry(sanitize_for_json(entry))
            except Exception as e:  # noqa: BLE001 — an observer must
                # not take down the metrics path (telemetry observer
                # discipline).
                logger.debug("metrics on_entry failed: %s: %s",
                             type(e).__name__, e)

    def record(self, step: int, metrics: dict, epoch: int = 0) -> None:
        if not self.enabled or self.log_every <= 0:
            return
        if step % self.log_every != 0:
            return
        now = time.perf_counter()
        if self._last_time is None:
            # First row: compile/warmup dominated — no throughput
            # numbers, flagged so consumers (and the summarizer's
            # trajectory stats) can exclude it. The clean window
            # starts here.
            entry = {"epoch": epoch, "step": step,
                     "loss": float(metrics.get("loss", float("nan"))),
                     "warmup": True}
            self._append(entry)
            logger.info("step %d | epoch %d | loss %.6f | (warmup "
                        "row: throughput window starts here)",
                        step, epoch, entry["loss"])
            self._last_time = now
            self._last_step = step
            return
        dsteps = max(step - self._last_step, 1)
        dt = max(now - self._last_time, 1e-9)
        steps_per_sec = dsteps / dt
        samples_per_sec = steps_per_sec * self.samples_per_step
        entry = {
            "epoch": epoch,
            "step": step,
            "loss": float(metrics.get("loss", float("nan"))),
            "steps_per_sec": steps_per_sec,
            "samples_per_sec_per_chip": samples_per_sec / self.num_devices,
        }
        if self.flops_per_sample:
            flops_per_chip = (samples_per_sec * self.flops_per_sample
                              / self.num_devices)
            entry["mfu"] = compute_mfu(flops_per_chip, self.device_kind)
        self._append(entry)
        logger.info(
            "step %d | epoch %d | loss %.6f | %.1f samples/s/chip%s",
            step, epoch, entry["loss"], entry["samples_per_sec_per_chip"],
            f" | mfu {entry['mfu']:.3f}" if "mfu" in entry else "")
        self._last_time = now
        self._last_step = step

    def record_scalar(self, step: int, name: str, value: float,
                      epoch: int = 0) -> None:
        """Unthrottled single-scalar entry (eval metrics, one-off
        events). Does not touch the throughput window."""
        if not self.enabled:
            return
        self._append({"epoch": epoch, "step": step,
                      name: float(value)})
        logger.info("step %d | epoch %d | %s %.6f", step, epoch, name,
                    float(value))
