"""Failure detection / graceful preemption handling.

The reference's failure model is crash-restart-resume: bounded
rendezvous retries at bring-up (cloud-init.tftpl:18-32) plus
checkpoint-based recovery on restart (src/distributed_trainer.py:97-105;
SURVEY.md §5.3). On TPU the dominant failure is *planned*: preemptible /
spot VMs receive SIGTERM ~30s before shutdown. This module turns that
signal into a cooperative stop flag the trainer polls at step
granularity, so the final checkpoint lands before the VM disappears —
strictly better recovery latency than restart-from-last-save_every.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger(__name__)


class PreemptionGuard:
    """Converts SIGTERM/SIGINT into a polled stop flag.

    Usage::

        guard = PreemptionGuard.install()
        for epoch in ...:
            for batch in ...:
                trainer.train_step(batch)
                if guard.should_stop:
                    break
        # trainer saves + exits cleanly

    Thread-safe; also usable as a plain flag in tests via ``trigger``.
    """

    def __init__(self) -> None:
        self._stop = threading.Event()
        self._prev_handlers: dict[int, object] = {}

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()

    def trigger(self, reason: str = "manual") -> None:
        if not self._stop.is_set():
            logger.warning("stop requested (%s): finishing step, "
                           "saving checkpoint, exiting", reason)
        self._stop.set()

    def _handler(self, signum, frame):  # pragma: no cover - signal path
        del frame
        self.trigger(signal.Signals(signum).name)

    @classmethod
    def install(cls, signals: tuple[int, ...] = (signal.SIGTERM,)
                ) -> "PreemptionGuard":
        """Install handlers (main thread only). SIGTERM is what both GCE
        preemption and orchestrators (k8s, slurm) deliver first."""
        guard = cls()
        for s in signals:
            guard._prev_handlers[s] = signal.getsignal(s)
            signal.signal(s, guard._handler)
        return guard

    def uninstall(self) -> None:
        for s, prev in self._prev_handlers.items():
            signal.signal(s, prev)
        self._prev_handlers.clear()
