"""HBM budget estimation for training configs.

Answers "will this config fit on this chip / how many chips do I need?"
before burning a compile: params + grads + optimizer state are exact
from shapes; activations use the standard transformer accounting
(per-layer residuals and block internals, scaled by the remat policy).
The reference has nothing comparable — its models are Linear(20,1) —
but the BASELINE.json 1B/7B FSDP configs live or die on this arithmetic.

Estimates are per chip: pass ``fsdp`` (and ``tp``) shard counts to see
the sharded footprint.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np

_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2}

# Concurrent-copies multiplier on per-layer scan residuals, calibrated
# on a v5e OOM report (see estimate_transformer_memory docstring).
_SCAN_RESIDUAL_OVERHEAD = 2.0

# Known per-chip HBM capacities (GiB) for planning output.
HBM_GIB = {
    "v4": 32.0,
    "v5e": 16.0,
    "v5 lite": 16.0,
    "v5p": 95.0,
    "v6e": 32.0,
}


def param_count(params) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def state_bytes_per_device(tree, shardings) -> int:
    """Exact per-device residency of a state tree (params + grads'
    template + optimizer moments) given its shardings: each leaf's
    bytes divided by the product of the mesh-axis sizes its
    PartitionSpec shards over — replicated leaves count in full on
    every device, ``pinned_host``-offloaded leaves count zero (they
    live in host RAM between steps).

    This is the model-agnostic cross-check the HBM telemetry stream
    (telemetry/hbm.py) carries alongside ``memory_stats()`` samples:
    a growing gap between this number and ``bytes_in_use`` is
    activations/fragmentation, not state."""
    def leaf_bytes(x, sh) -> int:
        if getattr(sh, "memory_kind", None) == "pinned_host":
            return 0
        nbytes = int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        spec = getattr(sh, "spec", None)
        if spec is None:
            return nbytes
        sizes = dict(zip(sh.mesh.axis_names, sh.mesh.devices.shape))
        div = 1
        for part in spec:
            if part is None:
                continue
            for axis in ((part,) if isinstance(part, str) else part):
                div *= sizes.get(axis, 1)
        return -(-nbytes // div)

    counted = jax.tree.map(leaf_bytes, tree, shardings)
    return int(sum(jax.tree.leaves(counted)))


@dataclass
class MemoryEstimate:
    params_gib: float
    grads_gib: float
    opt_gib: float
    activations_gib: float

    @property
    def total_gib(self) -> float:
        return (self.params_gib + self.grads_gib + self.opt_gib
                + self.activations_gib)

    def fits(self, device_kind: str, headroom: float = 0.85) -> bool:
        """Whether the estimate fits in ``device_kind``'s HBM, leaving
        ``1 - headroom`` for XLA scratch/fragmentation."""
        cap = HBM_GIB.get(device_kind.lower())
        if cap is None:
            raise ValueError(f"unknown device kind '{device_kind}'; "
                             f"known: {sorted(HBM_GIB)}")
        return self.total_gib <= cap * headroom


def estimate_transformer_memory(
        tf_cfg, batch_per_chip: int, seq_len: int,
        optimizer: str = "adamw", fsdp: int = 1, tp: int = 1,
        offload_opt: bool = False,
) -> MemoryEstimate:
    """Per-chip training footprint of a ``TransformerConfig``.

    - params/grads: n_params × dtype bytes, sharded over fsdp×tp;
    - optimizer: AdamW = two fp32 moments (+ fp32 master view is not
      kept — params are the master copy), SGD = none;
    - activations (per layer, batch B, seq S, width D, ffn F), as
      (saved-set coefficient) × ``_SCAN_RESIDUAL_OVERHEAD``. The two
      knobs encode ONE measurement jointly and must be recalibrated
      together: a v5e OOM report at B=16 (no remat) showed six live
      1.12 GiB [L,B,S,F] buffers — 3× the two logical F-wide saves,
      plus further D-wide copies below the report's top-20. The model
      here is: saved-set coefficients count logical saves ×2 for
      XLA's forward temporaries (F term: 2·F → 4·F), and the global
      ×2 overhead covers fwd-stack/bwd-consumption concurrency —
      jointly 8·F vs the ≥6·F observed live at peak, one notch
      conservative. Per policy (saved set before the global ×2):
        no remat:        6·D + 4·F
        remat mlp:       ≈ 8·D (everything but the F-wide MLP pair)
        remat selective: ≈ 3·D (residual + attention output)
        remat full:      ≈ 2·D (carry + saved input)
      plus the loss head: with ``loss_impl='dense'`` the B·S·V fp32
      logits buffer (often the true peak); with the default fused
      chunked xent (ops/xent.py) only a chunk_rows·V fp32 tile plus the
      per-token lse is ever alive.
    These are planning numbers, not allocator ground truth — XLA
    fusion/padding moves them ±20%.
    """
    c = tf_cfg
    pb = _BYTES[c.param_dtype]
    ab = _BYTES[c.dtype]
    d_ff = c.d_ff or 4 * c.d_model

    # Exact by construction: trace init shapes abstractly (no compile,
    # no allocation) instead of shadow-bookkeeping the model layout.
    from distributed_training_tpu.models.transformer import Transformer
    shapes = jax.eval_shape(Transformer(c).init, jax.random.PRNGKey(0))
    n_params = param_count(shapes)

    model_shards = max(1, fsdp) * max(1, tp)
    params_b = n_params * pb / model_shards
    grads_b = n_params * pb / model_shards
    # offload_opt (train.offload_opt_state) moves moments to pinned
    # host RAM BETWEEN steps, but the current trainer streams the whole
    # tree back on-device for the compiled step (trainer.py
    # train_step), so the per-step peak this estimate feeds fits()
    # still includes the full optimizer state. The flag therefore buys
    # no planning headroom until the step itself consumes moments from
    # host memory (XLA host-offload annotations — the documented
    # upgrade path in train/state.py). Use optimizer="adafactor" when
    # the plan needs genuinely small moments.
    del offload_opt
    if optimizer == "adamw":
        opt_b = 2 * n_params * 4 / model_shards
    elif optimizer == "adafactor":
        # Factored second moment: rows+cols per matrix ≈ n_params /
        # min(dim); ~2% of params is a safe planning envelope.
        opt_b = 0.02 * n_params * 4 / model_shards
    else:  # sgd (no momentum)
        opt_b = 0.0

    B, S, D, F = batch_per_chip, seq_len, c.d_model, d_ff
    if not c.remat:
        act_per_layer = (6 * D + 4 * F) * B * S * ab
    elif c.remat_policy == "selective":
        act_per_layer = 3 * D * B * S * ab
    elif c.remat_policy == "mlp":
        act_per_layer = 8 * D * B * S * ab
    elif c.remat_policy == "mlp_pre":
        # "mlp" saves + the one F-wide pre-gelu tensor. The tag only
        # exists in the dense MLP branch: with MoE active the policy
        # degrades to "mlp" (transformer.py policy selection) and the
        # F-wide save must not be charged.
        moe = getattr(c, "moe_num_experts", 0)
        act_per_layer = (8 * D + (F if not moe else 0)) * B * S * ab
    else:  # full
        act_per_layer = 2 * D * B * S * ab
    acts_b = c.n_layers * act_per_layer * _SCAN_RESIDUAL_OVERHEAD
    if getattr(c, "loss_impl", "fused") == "dense":
        # fp32 logits + their softmax residual dominate.
        acts_b += B * S * c.vocab_size * 4 / max(1, tp)
    else:
        from distributed_training_tpu.ops.xent import DEFAULT_CHUNK_ROWS
        acts_b += DEFAULT_CHUNK_ROWS * c.vocab_size * 4  # live tile
        acts_b += B * S * (4 + D * ab)  # lse + saved hidden states

    gib = 1 / (1024 ** 3)
    return MemoryEstimate(
        params_gib=params_b * gib,
        grads_gib=grads_b * gib,
        opt_gib=opt_b * gib,
        activations_gib=acts_b * gib,
    )
