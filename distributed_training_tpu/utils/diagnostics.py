"""Race / divergence / numerical-health diagnostics.

The reference's only divergence tooling is a human diffing per-rank
grad/weight-norm log lines (src/playground/ddp_script.py:149-164;
SURVEY.md §5.2). Here the checks are compiled collectives:

- ``replica_divergence``: are the data-parallel replicas of every param
  bitwise-in-sync? Computed as (max - min) over replicas of a per-leaf
  fingerprint, with a single psum-family reduction — the SPMD
  formalization of "diff the rank logs".
- ``check_finite``: which leaves contain NaN/Inf, as a host-side report
  (the trainer's in-step ``nan_guard`` skips bad updates; this is the
  post-mortem view).
"""

from __future__ import annotations

import collections
import logging
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_training_tpu.runtime import BATCH_AXES

logger = logging.getLogger(__name__)


def _fingerprint(x: jax.Array) -> jax.Array:
    """Order-stable int32 scalar fingerprint of a tensor's bits.
    float-sum fingerprints can collide on permuted values and round away
    small diffs; position-weighted int sums (wrapping overflow is fine —
    it is deterministic and identical across in-sync replicas) are
    sensitive to any elementwise change."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    idx = jnp.arange(bits.size, dtype=jnp.int32).reshape(bits.shape)
    return jnp.sum(bits * (idx % 8191 + 1))


# jit/shard_map cache: building a fresh closure per call would recompile
# the whole-params program on every periodic check. LRU-bounded: the
# key holds a Mesh (and through the jitted fn, its devices), so an
# unbounded dict pins every mesh a long test session ever built.
_DIVERGENCE_FNS: "collections.OrderedDict" = collections.OrderedDict()
_DIVERGENCE_CACHE_MAX = 8


def clear_divergence_cache() -> None:
    """Drop all cached divergence programs (test isolation hook)."""
    _DIVERGENCE_FNS.clear()


def _divergence_fn(mesh: Mesh, axes: tuple[str, ...],
                   specs_treedef, specs_leaves: tuple):
    key = (mesh, axes, specs_treedef, specs_leaves)
    fn = _DIVERGENCE_FNS.get(key)
    if fn is not None:
        _DIVERGENCE_FNS.move_to_end(key)
    if fn is None:
        in_specs = jax.tree_util.tree_unflatten(
            specs_treedef, list(specs_leaves))
        out_specs = jax.tree_util.tree_unflatten(
            specs_treedef, [P()] * len(specs_leaves))

        def per_replica(tree):
            def spread(x):
                f = _fingerprint(x)
                hi = f
                lo = f
                for a in axes:
                    hi = jax.lax.pmax(hi, a)
                    lo = jax.lax.pmin(lo, a)
                # int32 wrap-around subtraction is still 0 ⇔ equal.
                return jnp.abs(hi - lo)
            return jax.tree.map(spread, tree)

        fn = jax.jit(shard_map(per_replica, mesh=mesh,
                               in_specs=(in_specs,),
                               out_specs=out_specs, check_rep=False))
        _DIVERGENCE_FNS[key] = fn
        while len(_DIVERGENCE_FNS) > _DIVERGENCE_CACHE_MAX:
            _DIVERGENCE_FNS.popitem(last=False)
    return fn


def replica_divergence(params: Any, mesh: Mesh,
                       axes: tuple[str, ...] = BATCH_AXES,
                       param_specs: Any = None) -> dict:
    """Max absolute fingerprint spread across data-parallel replicas,
    per param leaf. 0 everywhere ⇔ replicas identical over ``axes``.

    ``param_specs``: PartitionSpec pytree describing how ``params`` are
    actually sharded (a strategy's ``specs_for_tree``). Defaults to
    fully-replicated specs — correct for DDP; for FSDP/TP pass the real
    specs (so shards are fingerprinted in place, no all-gather) and
    restrict ``axes`` to axes the params are replicated over.

    Under single-controller SPMD, XLA keeps replicated values consistent
    by construction; this check matters for multi-process runs (where
    each host materializes its own addressable shards) and as a
    regression harness for custom-collective code (playground,
    hand-written psum paths)."""
    axes = tuple(a for a in axes
                 if dict(zip(mesh.axis_names, mesh.devices.shape))
                 .get(a, 1) > 1)
    if not axes:
        return {"max_divergence": 0, "leaves": {}}

    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(), params)
    # Specs must not shard over the axes we compare across.
    used = {a for s in jax.tree.leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
        for part in s if part is not None
        for a in ((part,) if isinstance(part, str) else part)}
    overlap = used & set(axes)
    if overlap:
        raise ValueError(
            f"params are sharded over {sorted(overlap)}; there are no "
            f"replicas to compare over those axes — restrict `axes`")

    leaves, treedef = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    fn = _divergence_fn(mesh, axes, treedef, tuple(leaves))
    spreads = fn(params)
    flat, _ = jax.tree_util.tree_flatten_with_path(spreads)
    leaves_out = {jax.tree_util.keystr(path): int(v) for path, v in flat}
    worst = max(leaves_out.values(), default=0)
    if worst > 0:
        bad = {k: v for k, v in leaves_out.items() if v > 0}
        logger.warning("replica divergence detected: %s", bad)
    return {"max_divergence": worst, "leaves": leaves_out}


def check_finite(tree: Any) -> dict:
    """Host-side NaN/Inf report: count of non-finite entries per leaf;
    empty dict means all finite. Summing the (rare) non-finite indicator
    in float32 is exact below 2^24 and saturates-but-stays-positive
    above, so a poisoned leaf can never be reported clean — unlike a
    float mean of isfinite (rounds sparse NaNs in big leaves to 0) or an
    int32 sum (wraps past 2^31, possibly to <=0)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(lambda x: jnp.sum(
            (~jnp.isfinite(x)).astype(jnp.float32)), tree))
    bad = {jax.tree_util.keystr(path): int(v)
           for path, v in flat if float(v) > 0}
    if bad:
        logger.error("non-finite values: %s", bad)
    return bad


def assert_replicas_in_sync(params: Any, mesh: Mesh,
                            axes: tuple[str, ...] = BATCH_AXES) -> None:
    """Test/debug assertion form of ``replica_divergence``."""
    report = replica_divergence(params, mesh, axes)
    if report["max_divergence"] > 0:
        bad = {k: v for k, v in report["leaves"].items() if v > 0}
        raise AssertionError(f"replicas diverged: {bad}")


def grad_global_norm_by_module(grads: Any) -> dict[str, float]:
    """Per-top-level-module gradient norms (debug aid for loss spikes)."""
    out = {}
    if isinstance(grads, dict):
        for key, sub in grads.items():
            sq = jax.tree.reduce(
                lambda acc, g: acc + jnp.sum(jnp.square(
                    g.astype(jnp.float32))), sub, jnp.zeros(()))
            out[key] = float(jnp.sqrt(sq))
    else:
        out["all"] = float(
            jnp.sqrt(jax.tree.reduce(
                lambda acc, g: acc + jnp.sum(jnp.square(
                    g.astype(jnp.float32))), grads, jnp.zeros(()))))
    return out


def summarize_state(state: Any) -> dict:
    """One-call health summary: finiteness + basic scale stats."""
    params = (state["params"]
              if isinstance(state, dict) and "params" in state
              else state)
    nonfinite = check_finite(params)
    norms = grad_global_norm_by_module(params)
    return {"nonfinite": nonfinite, "param_norms": norms,
            "healthy": not nonfinite}
