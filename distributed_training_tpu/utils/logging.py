"""Structured per-process logging.

Parity with the reference's ``setup_logging`` (src/distributed_trainer.py:
214-240: root logger → file + stdout, timestamped) and the playground's
per-rank log files (ddp_script.py:56-92), minus the double-registration
wart (§5.5): handler setup is idempotent.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False

FORMAT = ("%(asctime)s [%(levelname)s] p%(process)d %(name)s: "
          "%(message)s")


def setup_logging(level: str = "INFO", log_file: str | None = None,
                  process_index: int = 0, force: bool = False) -> None:
    """Configure root logging once per process.

    Non-coordinator processes log at WARNING to the console (so a pod's
    worth of workers doesn't interleave) but keep full logs in their
    per-process file — the reference's per-rank-file idea
    (ddp_script.py:70-78) applied to production.
    """
    global _CONFIGURED
    if _CONFIGURED and not force:
        return
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))

    console = logging.StreamHandler(sys.stdout)
    console.setFormatter(logging.Formatter(FORMAT))
    if process_index != 0:
        console.setLevel(logging.WARNING)
    root.addHandler(console)

    if log_file:
        base, ext = os.path.splitext(log_file)
        path = (log_file if process_index == 0
                else f"{base}.p{process_index}{ext}")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fh = logging.FileHandler(path)
        fh.setFormatter(logging.Formatter(FORMAT))
        root.addHandler(fh)
    _CONFIGURED = True
