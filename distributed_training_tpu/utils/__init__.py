"""Utilities: logging, metrics/MFU, profiling, divergence guards."""
