"""ctypes bindings for the native data-loader kernels (dtt_native.cpp).

Build-on-first-import: compiles ``dtt_native.cpp`` with g++ into a
shared library cached beside the source (keyed on a source hash, so
edits rebuild automatically). Everything degrades gracefully — if no
compiler is present or the build fails, ``available()`` is False and
callers (data/datasets.py) fall back to NumPy. Both entry points are
**bit-identical** across paths (gather: same fancy-index semantics;
fill_tokens: the NumPy path replays the native SplitMix64 stream) —
only speed differs, never data.

This is the framework's native runtime component for host-side IO: the
TPU analogue of torch's C++ DataLoader workers the reference trains
through (src/distributed_trainer.py:204-211). Device-side compute stays
in XLA/Pallas — host batch assembly is the part that belongs in C++.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "dtt_native.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

DEFAULT_THREADS = int(os.environ.get("DTT_NATIVE_THREADS", "0"))  # 0=auto


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_build_dir(), f"dtt_native_{tag}.so")


def _compile(path: str) -> None:
    # -march=native is safe: the .so is cached per machine, not shipped.
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
           "-fPIC", "-pthread", _SRC, "-o", path]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(path))
    os.close(fd)  # g++ rewrites the (safely created) file in place
    try:
        subprocess.run(cmd[:-1] + [tmp], check=True,
                       capture_output=True)
        os.replace(tmp, path)  # atomic under concurrent builders
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("DTT_NATIVE_DISABLE"):
            return None
        try:
            path = _lib_path()
            if not os.path.exists(path):
                _compile(path)
            lib = ctypes.CDLL(path)
            i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)
            lib.dtt_gather_rows.restype = ctypes.c_int
            lib.dtt_gather_rows.argtypes = [
                ctypes.c_char_p, i64, i64,
                ctypes.POINTER(ctypes.c_int64), i64,
                ctypes.c_char_p, ctypes.c_int]
            lib.dtt_fill_tokens.restype = None
            lib.dtt_fill_tokens.argtypes = [i64, i64, i32p, i64,
                                            ctypes.c_int]
            _LIB = lib
        except Exception as e:  # compiler missing, bad toolchain, ...
            logger.warning("native kernels unavailable (%s); "
                           "falling back to NumPy", e)
    return _LIB


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, indices: np.ndarray,
                n_threads: int = DEFAULT_THREADS) -> np.ndarray:
    """``src[indices]`` (row gather) — multithreaded when the native
    library is available, NumPy fancy-indexing otherwise. Exact-equal
    outputs either way, including NumPy's negative-index wrapping and
    its IndexError on out-of-range."""
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    lib = _load()
    # Fall back for shapes the kernel doesn't cover: 0-d/non-row
    # sources, multi-dim index arrays, and non-contiguous sources
    # (copying a whole non-contiguous column would cost O(dataset) per
    # batch — NumPy gathers views without that).
    if (lib is None or src.ndim == 0 or idx.ndim != 1
            or not src.flags.c_contiguous):
        return src[idx]
    row_bytes = src.dtype.itemsize * int(
        np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0:
        return src[idx]
    n = src.shape[0]
    if idx.size and (idx.min() < -n or idx.max() >= n):
        raise IndexError(f"gather index out of range [-{n}, {n})")
    if idx.size and idx.min() < 0:  # NumPy wrap semantics
        idx = np.where(idx < 0, idx + n, idx)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    rc = lib.dtt_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p), n, row_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx),
        out.ctypes.data_as(ctypes.c_char_p), n_threads)
    if rc != 0:
        raise IndexError(f"gather index out of range [-{n}, {n})")
    return out


_FILL_BLOCK = 4096  # must match dtt_native.cpp's block constant
_SM64_GAMMA = 0x9E3779B97F4A7C15
_SM64_M1 = 0xBF58476D1CE4E5B9
_SM64_M2 = 0x94D4A2CA9C8DE917
_FILL_STREAM = 0xD1342543DE82EF95


def _fill_tokens_numpy(seed: int, vocab: int, n: int) -> np.ndarray:
    """Vectorized uint64 NumPy reproduction of the native SplitMix64
    stream (dtt_native.cpp: dtt_fill_tokens) — *bit-identical* output.

    This matters on multi-host pods: every host builds the synthetic
    corpus locally and the data path assumes the copies are identical.
    If native build availability differed across hosts and the fallback
    drew a different stream, per-host corpora would silently diverge
    (the ADVICE.md round-1 medium finding) — so the fallback is exact,
    not merely "equally valid".

    Per 4096-token block ``b``: state ``s0 = seed ^ (STREAM * (b+1))``;
    draw ``i`` mixes ``s0 + (i+1) * GAMMA`` through the SplitMix64
    finalizer; token = mix % vocab. All modular uint64 — NumPy unsigned
    arithmetic wraps exactly like C.
    """
    n_blocks = (n + _FILL_BLOCK - 1) // _FILL_BLOCK
    seed_u = np.uint64(seed & 0xFFFFFFFFFFFFFFFF)
    b = np.arange(1, n_blocks + 1, dtype=np.uint64)
    s0 = seed_u ^ (np.uint64(_FILL_STREAM) * b)          # (n_blocks,)
    i = np.arange(1, _FILL_BLOCK + 1, dtype=np.uint64)
    z = s0[:, None] + i[None, :] * np.uint64(_SM64_GAMMA)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(_SM64_M1)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(_SM64_M2)
    z = z ^ (z >> np.uint64(31))
    return (z % np.uint64(vocab)).astype(np.int32).reshape(-1)[:n]


def fill_tokens(seed: int, vocab: int, n: int,
                n_threads: int = DEFAULT_THREADS) -> np.ndarray:
    """n int32 tokens uniform in [0, vocab), deterministic in seed
    (thread-count independent). Native and NumPy paths produce
    bit-identical streams, so mixed-availability hosts agree."""
    out = np.empty(n, dtype=np.int32)
    lib = _load()
    if lib is None:
        return _fill_tokens_numpy(seed, vocab, n)
    lib.dtt_fill_tokens(
        seed, vocab, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, n_threads)
    return out
