// Native host-side data-loader kernels.
//
// The reference's data path leans on torch's C++ DataLoader machinery
// (worker processes + pinned-memory collation; src/distributed_trainer
// .py:204-211). The TPU-native analogue keeps devices fed from the
// host: batch assembly is a strided row-gather over columnar NumPy
// storage, which NumPy executes single-threaded. These kernels do the
// same gather (and the synthetic-data fills) multithreaded, bound via
// ctypes from distributed_training_tpu/native/__init__.py.
//
// Build: g++ -O3 -march=native -shared -fPIC (driven by the Python
// wrapper, cached next to this file).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int clamp_threads(int requested, std::int64_t work_items) {
    unsigned hw = std::thread::hardware_concurrency();
    if (hw == 0) hw = 4;
    std::int64_t cap = std::min<std::int64_t>(
        requested > 0 ? requested : static_cast<std::int64_t>(hw),
        work_items);
    return static_cast<int>(std::max<std::int64_t>(cap, 1));
}

template <typename Fn>
void parallel_chunks(std::int64_t n, int n_threads, Fn fn) {
    if (n_threads <= 1 || n < 2) {
        fn(0, n);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    std::int64_t chunk = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        std::int64_t lo = t * chunk;
        std::int64_t hi = std::min(n, lo + chunk);
        if (lo >= hi) break;
        pool.emplace_back([=] { fn(lo, hi); });
    }
    for (auto& th : pool) th.join();
}

// SplitMix64: tiny, seedable, statistically solid for synthetic data.
inline std::uint64_t splitmix64(std::uint64_t& s) {
    std::uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d4a2ca9c8de917ULL;
    return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Gather rows: out[i, :] = src[idx[i], :], rows treated as raw bytes
// (dtype-agnostic). Returns 0 on success, -1 on an out-of-range index
// (checked up front so partial output is never silently wrong).
int dtt_gather_rows(const char* src, std::int64_t n_src_rows,
                    std::int64_t row_bytes, const std::int64_t* idx,
                    std::int64_t n_idx, char* out, int n_threads) {
    for (std::int64_t i = 0; i < n_idx; ++i) {
        if (idx[i] < 0 || idx[i] >= n_src_rows) return -1;
    }
    // Thread spawn costs ~10us; only worth it for multi-MB gathers.
    int threads = (n_idx * row_bytes < (1 << 20))
                      ? 1
                      : clamp_threads(n_threads, n_idx);
    parallel_chunks(n_idx, threads, [=](std::int64_t lo, std::int64_t hi) {
        for (std::int64_t i = lo; i < hi; ++i) {
            std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                        static_cast<std::size_t>(row_bytes));
        }
    });
    return 0;
}

// Fill `n` int32 tokens uniformly in [0, vocab). Deterministic in
// (seed); parallel chunks reseed per-chunk so the output is identical
// for any thread count.
void dtt_fill_tokens(std::int64_t seed, std::int64_t vocab,
                     std::int32_t* out, std::int64_t n, int n_threads) {
    const std::int64_t block = 4096;
    std::int64_t n_blocks = (n + block - 1) / block;
    int threads = clamp_threads(n_threads, n_blocks);
    parallel_chunks(n_blocks, threads,
                    [=](std::int64_t lo, std::int64_t hi) {
        for (std::int64_t b = lo; b < hi; ++b) {
            std::uint64_t s = static_cast<std::uint64_t>(seed) ^
                              (0xd1342543de82ef95ULL *
                               static_cast<std::uint64_t>(b + 1));
            std::int64_t end = std::min(n, (b + 1) * block);
            for (std::int64_t i = b * block; i < end; ++i) {
                out[i] = static_cast<std::int32_t>(
                    splitmix64(s) % static_cast<std::uint64_t>(vocab));
            }
        }
    });
}

}  // extern "C"
