"""ResNet-18 for CIFAR-10 (BASELINE.json config 2, 8-way data parallel).

Functional pytree implementation over ``lax.conv_general_dilated`` (NHWC,
the TPU-native conv layout). Normalization is GroupNorm(32) rather than
BatchNorm: BN's running statistics are mutable cross-batch state that
fights the pure-pytree train step and syncs badly across data-parallel
replicas; GN is the standard stateless substitute with equivalent
CIFAR-scale accuracy. Documented as a deliberate divergence in
docs/parity.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_tpu.models.base import normal_init


def _conv(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                groups: int = 32) -> jax.Array:
    dt = x.dtype
    B, H, W, C = x.shape
    g = min(groups, C)
    while C % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mu = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
    xf = (xf - mu) * jax.lax.rsqrt(var + 1e-5)
    xf = xf.reshape(B, H, W, C)
    return (xf * scale + bias).astype(dt)


@dataclass
class ResNet:
    """ResNet-18 (2-2-2-2 basic blocks), CIFAR stem (3x3, no max-pool)."""

    num_classes: int = 10
    width: int = 64
    stage_sizes: list[int] = field(default_factory=lambda: [2, 2, 2, 2])
    dtype: str = "float32"
    loss_name: str = "xent"
    batch_keys: ClassVar[tuple[str, ...]] = ("x", "y")

    def _stages(self):
        chans = [self.width * (2 ** i) for i in range(len(self.stage_sizes))]
        return list(zip(self.stage_sizes, chans))

    def init(self, rng: jax.Array):
        pdt = jnp.float32
        n_keys = 4 + sum(self.stage_sizes) * 6
        ks = iter(jax.random.split(rng, n_keys))

        def conv_w(k, kh, kw, cin, cout):
            # He/Kaiming normal (torch conv default family)
            std = float(np.sqrt(2.0 / (kh * kw * cin)))
            return normal_init(k, (kh, kw, cin, cout), std, pdt)

        params: dict = {
            "stem": {"w": conv_w(next(ks), 3, 3, 3, self.width),
                     "scale": jnp.ones((self.width,), pdt),
                     "bias": jnp.zeros((self.width,), pdt)},
        }
        cin = self.width
        for si, (blocks, cout) in enumerate(self._stages()):
            stage = []
            for bi in range(blocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                blk = {
                    "conv1": conv_w(next(ks), 3, 3, cin, cout),
                    "gn1": {"scale": jnp.ones((cout,), pdt),
                            "bias": jnp.zeros((cout,), pdt)},
                    "conv2": conv_w(next(ks), 3, 3, cout, cout),
                    "gn2": {"scale": jnp.ones((cout,), pdt),
                            "bias": jnp.zeros((cout,), pdt)},
                }
                if stride != 1 or cin != cout:
                    blk["proj"] = conv_w(next(ks), 1, 1, cin, cout)
                stage.append(blk)
                cin = cout
            params[f"stage{si}"] = stage
        params["head"] = {
            "w": normal_init(next(ks), (cin, self.num_classes),
                             float(np.sqrt(1.0 / cin)), pdt),
            "b": jnp.zeros((self.num_classes,), pdt),
        }
        return params

    def apply(self, params, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.dtype(self.dtype))
        s = params["stem"]
        x = jax.nn.relu(_group_norm(_conv(x, s["w"]), s["scale"],
                                    s["bias"]))
        for si, (blocks, _cout) in enumerate(self._stages()):
            for bi in range(blocks):
                blk = params[f"stage{si}"][bi]
                stride = 2 if (si > 0 and bi == 0) else 1
                h = jax.nn.relu(_group_norm(
                    _conv(x, blk["conv1"], stride),
                    blk["gn1"]["scale"], blk["gn1"]["bias"]))
                h = _group_norm(_conv(h, blk["conv2"]),
                                blk["gn2"]["scale"], blk["gn2"]["bias"])
                shortcut = (_conv(x, blk["proj"], stride)
                            if "proj" in blk else x)
                x = jax.nn.relu(h + shortcut)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        logits = (x @ params["head"]["w"].astype(x.dtype)
                  + params["head"]["b"].astype(x.dtype))
        return logits.astype(jnp.float32)

    def loss(self, params, batch, rng: jax.Array, train: bool = True):
        del rng, train
        logits = self.apply(params, batch["x"])
        labels = batch["y"].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, labels[:, None], axis=-1))
        acc = jnp.mean((jnp.argmax(logits, -1) == labels)
                       .astype(jnp.float32))
        return loss, {"loss": loss, "accuracy": acc}

    def logical_axes(self):
        # Convs shard fine under the shape heuristic; annotate None.
        return None

    def flops_per_sample(self) -> float:
        # 2 flops/MAC, backward ≈ 2x forward; CIFAR 32x32 input.
        hw = 32 * 32
        total = 2 * 3 * 3 * 3 * self.width * hw
        cin = self.width
        for si, (blocks, cout) in enumerate(self._stages()):
            scale = 4 ** si  # spatial halving per stage
            for bi in range(blocks):
                total += 2 * 9 * cin * cout * hw // scale
                total += 2 * 9 * cout * cout * hw // scale
                cin = cout
        return 3.0 * total
