"""Model registry keyed by config ``model.name``."""

from __future__ import annotations

from typing import Any


def build_model(name: str, loss: str = "auto", dtype: str = "float32",
                **kwargs: Any):
    """Construct a model family from config.

    ``loss="auto"`` keeps each family's natural default (MLP → mse like
    the playground; transformer → next-token xent). The reference's
    degenerate trainer pairing is available as ``loss=prob_xent``
    (SURVEY.md §8 B5).
    """
    name = name.lower()
    if name == "mlp":
        from distributed_training_tpu.models.mlp import MLP
        loss_name = "mse" if loss == "auto" else loss
        return MLP(loss_name=loss_name, dtype=dtype, **kwargs)
    if name in ("transformer", "gpt2", "gpt2_125m", "gpt2_350m",
                "transformer_1b", "transformer_7b", "moe_transformer"):
        from distributed_training_tpu.models.transformer import (
            build_transformer,
        )
        return build_transformer(name, loss=loss, dtype=dtype, **kwargs)
    if name in ("resnet", "resnet18"):
        from distributed_training_tpu.models.resnet import ResNet
        return ResNet(dtype=dtype, **kwargs)
    raise ValueError(f"unknown model '{name}'")
