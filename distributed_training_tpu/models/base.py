"""Model protocol + shared initializers."""

from __future__ import annotations

from typing import Any, Mapping, Protocol, runtime_checkable

import jax
import jax.numpy as jnp
import numpy as np


@runtime_checkable
class Model(Protocol):
    """Functional model contract consumed by the Trainer.

    - ``init(rng)`` builds the param pytree (host-side shapes; sharding is
      applied by the trainer via the strategy's specs).
    - ``loss(params, batch, rng, train)`` returns ``(scalar_loss, metrics)``
      — models own their loss so the trainer stays model-agnostic (the
      reference hard-codes F.cross_entropy in the trainer,
      src/distributed_trainer.py:163; see SURVEY.md §8 B5 for why that
      pairing is degenerate).
    - ``logical_axes()`` mirrors the param pytree with per-dim logical
      names (``"embed"``, ``"mlp"``, ``"heads"``, ``"vocab"``, ...) that
      strategies map to mesh axes; ``None`` → shape heuristics.
    - ``flops_per_sample(seq_len?)`` powers MFU accounting.
    """

    def init(self, rng: jax.Array) -> Any: ...

    def loss(self, params: Any, batch: Mapping[str, jax.Array],
             rng: jax.Array, train: bool = True
             ) -> tuple[jax.Array, dict[str, jax.Array]]: ...

    def logical_axes(self) -> Any: ...

    def flops_per_sample(self) -> float: ...


def uniform_fan_in(rng: jax.Array, shape: tuple[int, ...], fan_in: int,
                   dtype=jnp.float32) -> jax.Array:
    """torch.nn.Linear default init: U(-1/sqrt(fan_in), 1/sqrt(fan_in)).

    Loss-curve parity with the reference requires matching this family
    (SURVEY.md §7 hard parts), not the distribution draw itself (different
    RNG streams) — curves are compared statistically, not bitwise.
    """
    bound = 1.0 / np.sqrt(max(fan_in, 1))
    return jax.random.uniform(rng, shape, dtype, -bound, bound)


def normal_init(rng: jax.Array, shape: tuple[int, ...], stddev: float,
                dtype=jnp.float32) -> jax.Array:
    return stddev * jax.random.normal(rng, shape, dtype)


def count_params(params: Any) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
