"""Decoder-only transformer family (GPT-2 125M → 7B, optional MoE).

The BASELINE.json model targets (configs 3-5). Designed TPU-first:

- **stacked layers + ``lax.scan``**: per-layer params are stacked along a
  leading depth axis and the decoder runs as a scan — compile time is
  O(1) in depth, the standard XLA-friendly shape for deep stacks.
- **remat**: ``cfg.remat`` wraps the scanned block in ``jax.checkpoint``
  (recompute activations in backward), the HBM-for-FLOPs trade the 7B
  config requires.
- **mixed precision**: compute dtype bf16 with fp32 params/optimizer and
  fp32 softmax/logits — MXU-native.
- **logical sharding axes** on every param (``vocab``, ``embed``,
  ``mlp``, ``heads``, ``kv``, ``expert``) so DP/FSDP/TP/EP layouts are
  pure strategy decisions; the batch's sequence dim can additionally be
  sharded over ``sp`` (ring attention) without touching this file.
- **attention dispatch** via ops.attention (naive reference / Pallas
  flash / ring).

No counterpart exists in the reference repo (its models are Linear
stubs, src/distributed_trainer.py:199); interface parity is with the
framework's own Model protocol.
"""

from __future__ import annotations

import functools
import math
import os
import warnings
from dataclasses import dataclass

import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from distributed_training_tpu.models.base import normal_init
from distributed_training_tpu.ops.attention import dot_product_attention


@dataclass
class TransformerConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int = 0          # 0 → = n_heads (MHA); < n_heads → GQA
    d_ff: int = 0                # 0 → 4 * d_model
    max_seq_len: int = 1024
    pos_encoding: str = "learned"  # "learned" (GPT-2) | "rope"
    dropout: float = 0.0
    tie_embeddings: bool = True
    dtype: str = "bfloat16"      # compute dtype
    param_dtype: str = "float32"
    remat: bool = False
    # "full": jax.checkpoint over the whole block — minimal memory,
    # recomputes everything incl. attention in the backward pass.
    # "selective": save attention outputs (small, B*S*D) and recompute
    # only the LN/MLP intermediates (the big B*S*4D buffers) — avoids
    # re-running the flash-attention kernel under remat, which costs
    # extra Pallas launches and compiles far more slowly.
    # "mlp": save every D-wide block tensor (MLP_POLICY_SAVED) so the
    # only recompute is the two (B, S, 4D) MLP hiddens — the single
    # largest residual class (measured on a v5e: six 1.12 GiB stacked
    # buffers at B=16, the whole OOM). Backward recompute = wi-matmul
    # + gelu (~+11% of fwd FLOPs) — the cheapest policy that unlocks
    # large batches.
    remat_policy: str = "selective"  # "full"|"selective"|"mlp"|"mlp_pre"
    attention_impl: str = "auto"
    # Sliding-window (Mistral-style) attention: query i attends keys
    # in [i − window + 1, i]. 0 = full causal. Flash kernels skip
    # out-of-band blocks (O(S·window) FLOPs); composes with every
    # impl: single-device and Ulysses apply the band over the full
    # local sequence; the ring maps it onto its per-block geometry in
    # GLOBAL positions (out-of-window blocks skipped, the boundary
    # block band-masked — the sequence-parallel option for windowed
    # GQA models whose head counts rule out Ulysses).
    attention_window: int = 0
    # Flash-kernel tile overrides (0 → ops/flash_attention defaults);
    # exposed so the bench sweep can tune them on real hardware.
    flash_block_q: int = 0
    flash_block_k: int = 0
    # lax.scan unroll over layers (1 = no unroll). Unrolling lets XLA
    # schedule/fuse across layer boundaries and shrink scan-stack
    # copies at the cost of compile time; a bench-sweep knob, numerics
    # are unchanged. Must divide n_layers (lax.scan requirement is
    # looser, but a ragged tail recompiles the remainder block).
    scan_unroll: int = 1
    pp_microbatches: int = 4      # microbatches when mesh pp > 1
    pp_schedule: str = "gpipe"    # "gpipe" | "interleaved"
    pp_virtual_stages: int = 2    # chunks/device when interleaved
    # MoE (expert-parallel): > 0 turns every MLP into a top-k routed
    # expert layer with a load-balancing aux loss.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_aux_weight: float = 0.01
    # "routed": capacity-bounded top-k dispatch (FLOPs ~independent of
    # the expert count at fixed top_k). "dense": every expert computes
    # every token, then masks — exact, O(E) FLOPs; kept as the
    # numerics reference and for tiny expert counts.
    moe_impl: str = "routed"
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 1024    # tokens per dispatch group (cap)
    loss_name: str = "xent"
    # "fused": chunked custom-VJP xent head (ops/xent.py) — never
    # materializes (B, S, V) logits, the HBM hog that caps batch size.
    # "dense": materialize fp32 logits + log_softmax (reference-style).
    loss_impl: str = "fused"
    # Row budget per xent scan chunk (ops/xent.py DEFAULT_CHUNK_ROWS);
    # the live (rows, V) fp32 logits buffer holds ~this many rows.
    # A bench-sweep knob: bigger chunks = fewer scan steps / bigger
    # matmuls vs a larger live buffer.
    xent_chunk_rows: int = 2048

    def __post_init__(self):
        if self.n_kv_heads == 0:
            self.n_kv_heads = self.n_heads
        if self.d_ff == 0:
            self.d_ff = 4 * self.d_model
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide into n_heads")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must divide into n_kv_heads")
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(
                f"dropout must be in [0, 1), got {self.dropout}")
        if self.moe_num_experts > 0 and self.moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor must be > 0, got "
                f"{self.moe_capacity_factor} (capacity 0 would silently "
                "drop every token)")
        if self.pp_schedule not in ("gpipe", "interleaved"):
            raise ValueError(
                f"unknown pp_schedule '{self.pp_schedule}' "
                "(expected 'gpipe' or 'interleaved')")
        if self.moe_impl not in ("routed", "dense"):
            raise ValueError(
                f"unknown moe_impl '{self.moe_impl}' "
                "(expected 'routed' or 'dense')")
        if self.loss_impl not in ("fused", "dense"):
            raise ValueError(
                f"unknown loss_impl '{self.loss_impl}' "
                "(expected 'fused' or 'dense')")
        if self.attention_window < 0:
            raise ValueError(
                f"attention_window must be >= 0, got "
                f"{self.attention_window}")
        if self.scan_unroll < 1 or self.n_layers % self.scan_unroll:
            raise ValueError(
                f"scan_unroll ({self.scan_unroll}) must be >= 1 and "
                f"divide n_layers ({self.n_layers})")
        if self.remat_policy not in ("full", "selective", "mlp",
                                     "mlp_pre"):
            # Validate here (not only in the remat branch of apply) so
            # a typo surfaces at construction even with remat=False or
            # on pp>1 meshes that bypass the single-stack remat path.
            raise ValueError(
                f"unknown remat_policy '{self.remat_policy}' "
                "(expected 'full', 'selective', 'mlp' or 'mlp_pre')")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# Allow-list for remat_policy="mlp": every D-wide tag _block emits,
# PLUS the flash kernel's custom-VJP residuals (flash_out/flash_lse,
# named in ops/flash_attention._flash_bhsd_fwd) — without them the
# backward re-runs the forward attention kernel even though attn_out
# itself is saved (measured r4: 31.8 ms/step of rematted pallas_call
# at batch 32). The F-wide MLP hiddens are the only block
# intermediates NOT here — they are the recompute this policy trades
# for HBM.
FLASH_RESIDUAL_NAMES = ("flash_out", "flash_lse")
MLP_POLICY_SAVED = ("ln1_out", "q_rope", "k_rope", "v_proj",
                    "attn_out", "resid_attn", "ln2_out",
                    *FLASH_RESIDUAL_NAMES)
# remat_policy="mlp_pre" additionally saves the ONE F-wide pre-gelu
# tensor, eliminating the wi-matmul recompute that "mlp" pays every
# backward (2*B*S*D*F FLOPs/layer ~ 8% of the step at gpt2_125m
# shapes); the only remaining recompute is the elementwise gelu, whose
# VJP input the saved pre-activation provides directly. HBM cost:
# B*S*F*2 bytes/layer (192 MiB at batch 32, gpt2_125m) — the
# compile-level memory ladder (10.76 GiB @32 with "mlp" on a 16 GiB
# v5e) says it fits; "mlp" remains the default for tighter configs.
MLP_PRE_POLICY_SAVED = (*MLP_POLICY_SAVED, "mlp_pre")

# DTT_NO_BHSD=1 keeps attention in the BSHD einsum layout (disables
# the _bhsd_fast path) — the chip session A/Bs the layout fast path on
# real hardware. Read once at import so the knob can't flip between
# already-compiled shapes mid-process (jit cache keys don't include
# env vars); process-start-only, like DTT_FLASH_SPLIT_BWD.
_NO_BHSD = os.environ.get("DTT_NO_BHSD", "0") not in ("", "0")

# Reference hyperparameters for the BASELINE.json ladder. Vocab is
# GPT-2's 50257 padded to 50304 (next multiple of 128): lane-aligned
# for the MXU and divisible by any power-of-two tp axis — the standard
# padding trick; the tokenizer never emits the padding ids.
PRESETS: dict[str, dict] = {
    "gpt2_125m": dict(vocab_size=50304, d_model=768, n_layers=12,
                      n_heads=12, max_seq_len=1024),
    "gpt2_350m": dict(vocab_size=50304, d_model=1024, n_layers=24,
                      n_heads=16, max_seq_len=1024),
    "transformer_1b": dict(vocab_size=50304, d_model=2048, n_layers=24,
                           n_heads=16, max_seq_len=2048,
                           pos_encoding="rope", tie_embeddings=False),
    "transformer_7b": dict(vocab_size=50304, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, max_seq_len=2048,
                           pos_encoding="rope", tie_embeddings=False,
                           remat=True),
}


def _dropout(x: jax.Array, rng: jax.Array, rate: float) -> jax.Array:
    """Inverted dropout: zero with prob ``rate``, scale kept values by
    1/(1-rate) so the expectation is unchanged."""
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate),
                     jnp.zeros((), x.dtype)).astype(x.dtype)


def _rope(q: jax.Array, k: jax.Array, positions: jax.Array,
          layout: str = "bshd") -> tuple:
    """Rotary position embedding on (B, S, H, D) or (B, H, S, D) q/k
    (``layout``: the sequence axis is 1 or 2 respectively)."""
    D = q.shape[-1]
    half = D // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
    if layout == "bhsd":
        cos = jnp.cos(angles)[None, None, :, :]  # (1, 1, S, half)
        sin = jnp.sin(angles)[None, None, :, :]
    else:
        cos = jnp.cos(angles)[None, :, None, :]  # (1, S, 1, half)
        sin = jnp.sin(angles)[None, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xr = jnp.concatenate([x1 * cos - x2 * sin,
                              x1 * sin + x2 * cos], axis=-1)
        return xr.astype(x.dtype)

    return rot(q), rot(k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_for_compute(w, fwd_sharding, bwd_sharding):
    """Asymmetric sharding constraint for FSDP weights: ``w`` is
    constrained ``fwd_sharding`` (replicated — the all-gather) in the
    forward, while the backward pins the cotangent to ``bwd_sharding``
    (the param's own layout) so gradient sync can lower to
    reduce-scatter. A plain with_sharding_constraint cannot express
    this: its VJP applies the SAME sharding to the cotangent."""
    return jax.lax.with_sharding_constraint(w, fwd_sharding)


def _gfc_fwd(w, fwd_sharding, bwd_sharding):
    return jax.lax.with_sharding_constraint(w, fwd_sharding), None


def _gfc_bwd(fwd_sharding, bwd_sharding, _res, g):
    return (jax.lax.with_sharding_constraint(g, bwd_sharding),)


_gather_for_compute.defvjp(_gfc_fwd, _gfc_bwd)


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array
                ) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale + bias).astype(dtype)


class Transformer:
    """Functional decoder-only transformer (Model protocol)."""

    batch_keys: tuple[str, ...] = ("tokens",)

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg
        self.mesh = None  # bound by the trainer for ring/ulysses
        # True while tracing the pipeline stage body (every mesh axis
        # is already manual there — _attention must not open a nested
        # shard_map).
        self._inside_pp = False
        self._compute_replicate = None  # bind_gather_for_compute
        self._compute_bwd_specs = {}

    def bind_mesh(self, mesh) -> None:
        """Give the model the device mesh (needed only for the
        sequence-parallel attention impls, ``'ring'`` and
        ``'ulysses'``: their shard_maps over the ``sp`` axis are
        constructed against a concrete mesh)."""
        self.mesh = mesh

    def bind_gather_for_compute(self, sharding,
                                bwd_specs: dict | None = None) -> None:
        """FSDP compute contract: constrain weights to ``sharding``
        (replicated) at their cast-to-compute-dtype sites, so XLA
        ALL-GATHERS each weight for its matmuls instead of running
        partial matmuls on weight shards and ALL-REDUCING the
        activations. Found by benchmarks/audit_collectives.py: with
        fsdp-sharded params and no constraint, the partitioner's cost
        model chose activation-shaped all-reduces — (B, S, V) logits,
        (B, S, H, D) qkv — which dwarf the parameter traffic FSDP is
        supposed to pay. The constraint sits INSIDE the layer scan on
        the per-layer slice (gathers are layer-by-layer, bf16, and
        transient) and on the embedding table / unembedding head at
        their single use sites.

        ``bwd_specs`` (path → NamedSharding of the PER-SLICE param
        layout, e.g. "attn/wq" → the stored spec minus the stacked
        layer dim) upgrades the constraint to an asymmetric custom
        VJP: replicated on forward (the gather), pinned to the param
        spec on backward — so each weight COTANGENT is born sharded
        and gradient sync can compile to reduce-scatter instead of
        all-reduce + slice. Without it, with_sharding_constraint's
        self-transposing VJP pins cotangents replicated and forces
        the 2x all-reduce (measured via audit_collectives)."""
        self._compute_replicate = sharding
        self._compute_bwd_specs = bwd_specs or {}

    def _w(self, p: jax.Array, dt, path: str | None = None
           ) -> jax.Array:
        """Cast a weight to compute dtype; under an FSDP gather-for-
        compute binding, also constrain it replicated (cast FIRST so
        the gather moves bf16, not fp32 masters). When the binding
        carries a per-leaf backward spec for ``path``, the asymmetric
        custom VJP is used so the weight's cotangent is born in the
        param layout (reduce-scatter-able) instead of replicated.
        Inside the pipeline's shard_map every mesh axis is manual — a
        named sharding constraint would be rejected at trace time —
        so the constraint is skipped there (stage params arrive
        already gathered per-stage by the pipeline's own specs)."""
        w = p.astype(dt)
        if self._compute_replicate is None or self._inside_pp:
            return w
        bwd = self._compute_bwd_specs.get(path) if path else None
        if bwd is None:
            return jax.lax.with_sharding_constraint(
                w, self._compute_replicate)
        return _gather_for_compute(w, self._compute_replicate, bwd)

    def _mesh_axis_sizes(self) -> dict:
        if self.mesh is None:
            return {}
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

    def _flash_active(self, seq_len: int) -> bool:
        """Will attention at ``seq_len`` run through the Pallas flash
        custom-VJP? Trace-time mirror of the dispatch in
        ops/attention.py, used to pick which attention-output name the
        remat allow-lists save.

        Mirrors ``flash_attention.supported()`` on the EFFECTIVE
        local-attention shapes rather than just the backend (ADVICE
        r4): a True here while dispatch demotes to naive per-shape
        saves residual names that never exist in the trace, and the
        backward silently recomputes all attention from the q/k/v tags
        — for ulysses that recompute includes the all-to-alls (always
        the case on CPU test meshes, where supported() is False).
        impl='flash' forces the kernel unconditionally at dispatch,
        and the ring names flash_out/flash_lse inside its own custom
        VJP for every inner path, so both resolve by impl alone."""
        from distributed_training_tpu.ops import flash_attention as fa
        c = self.cfg
        impl = c.attention_impl
        if impl == "naive":
            return False
        if impl == "ring":
            return True
        if impl in ("auto", "flash") and not self._tp_head_shardable():
            # Heads don't divide tp: the per-shard kernel cannot run
            # on a fractional head, so _attention demotes to naive —
            # the allow-lists must save attn_out accordingly.
            return False
        if impl == "flash":
            return True
        # 'auto' (single-device) and 'ulysses' (local attention over
        # the full sequence after the a2a; head counts shrink by
        # tp*sp, which preserves the H % Hkv ratio supported()
        # checks, so global counts predict the same answer).
        Dh = c.d_model // c.n_heads
        dt = jnp.dtype(c.dtype)
        q_s = jax.ShapeDtypeStruct((1, seq_len, c.n_heads, Dh), dt)
        kv = jax.ShapeDtypeStruct(
            (1, seq_len, c.n_kv_heads or c.n_heads, Dh), dt)
        return fa.supported(q_s, kv, kv, block_q=c.flash_block_q,
                            block_k=c.flash_block_k, layout="bshd")

    def _bhsd_fast(self, seq_len: int) -> bool:
        """Run the block's attention segment natively in (B, H, S, D)?

        The flash kernels work in BHSD; with the model's default BSHD
        einsum layout the wrapper transposes q/k/v in and the output
        back out every layer — and the backward recomputes those
        transposes from the saved BSHD residuals (measured r4:
        11.25 ms/step of standalone transposes at batch 32). When the
        single-device flash path is active, the qkv projections emit
        BHSD directly instead (XLA folds the output permutation into
        the matmul), rope and the residual tags follow, and no layout
        churn remains. Ring/Ulysses keep the BSHD contract — they
        shard the sequence axis and manage their own layouts.
        DTT_NO_BHSD=1 disables the fast path (chip A/B; read once at
        import — process-start-only, like DTT_FLASH_SPLIT_BWD)."""
        return (not _NO_BHSD
                and self.cfg.attention_impl in ("auto", "flash")
                and self._flash_active(seq_len))

    def _active_batch_axes(self) -> tuple:
        """Mesh batch axes with size > 1 (the data axes activations
        are actually sharded over) — single source for the pin
        constraint and the flash shard_map in_specs, which MUST agree
        (a mismatch is only caught by a topology compile)."""
        if self.mesh is None:
            return ()
        from distributed_training_tpu.runtime import BATCH_AXES
        sizes = self._mesh_axis_sizes()
        return tuple(a for a in BATCH_AXES if sizes.get(a, 1) > 1)

    def _tp_head_shardable(self) -> bool:
        """Can the flash kernel take a tp head shard? False when a
        bound mesh has tp > 1 that does not divide the (kv) head
        counts — the per-shard kernel cannot run on a fractional head,
        so dispatch demotes to naive and the remat allow-lists must
        save attn_out, not the flash residual names (the two MUST stay
        in sync: saving names that never exist makes the backward
        silently recompute all attention, the r4 31.8 ms/step bug
        class). Inside the pipeline's shard_map stage params are
        replicated over tp, so heads arrive whole."""
        if self.mesh is None or self._inside_pp:
            return True
        from distributed_training_tpu.runtime import AXIS_TP
        tp = self._mesh_axis_sizes().get(AXIS_TP, 1)
        if tp <= 1:
            return True
        c = self.cfg
        return not (c.n_heads % tp or (c.n_kv_heads or c.n_heads) % tp)

    def _pin_batch(self, x: jax.Array) -> jax.Array:
        """Constrain x's leading (batch) dim to the data axes; other
        dims unconstrained (sp layouts keep their sequence sharding).
        Applied OUTSIDE the jax.checkpoint boundary in the layer scan:
        the residual jax.checkpoint saves is its INPUT, and without
        the pin, sharding propagation through scan + the attention
        shard_map left the stacked per-layer residuals REPLICATED —
        at 7B/fsdp=16 an 8 GB bf16[L, B_global, S, D] buffer per
        device (caught by the device-less topology compile)."""
        if self.mesh is None or self._inside_pp:
            return x
        b_axes = self._active_batch_axes()
        if not b_axes:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        U = P.UNCONSTRAINED
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh,
                             P(b_axes, *([U] * (x.ndim - 1)))))

    def _gathered_table(self, tbl: jax.Array) -> jax.Array:
        """Constrain an embedding TABLE replicated at its lookup site.

        The token-embedding gather is the tp+sp+fsdp reshard cliff
        MULTICHIP_r05.json recorded: with the table model-sharded
        (vocab over tp, embed over fsdp) and the lookup's consumers
        demanding batch/seq-sharded activations, GSPMD cannot bridge
        the two shardings and falls back to "Involuntary full
        rematerialization" — replicating the ACTIVATION-scale gather
        result on every device (the SPMD001 finding analysis/ gates
        on; pinning the OUTPUT sharding does not help, the partitioner
        still computes the gather in the table's layout first).
        Replicating the TABLE instead makes the gather shard-local
        over batch/seq: one param-scale all-gather in compute dtype —
        the same gather-for-compute discipline the FSDP binding
        applies through ``_w`` (which already covers this table when
        bound, hence the ``_compute_replicate`` guard). Inside the
        pipeline's shard_map every axis is manual and stage params
        arrive gathered, so the constraint is skipped there."""
        if (self.mesh is None or self._inside_pp
                or self._compute_replicate is not None):
            return tbl
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.lax.with_sharding_constraint(
            tbl, NamedSharding(self.mesh, PartitionSpec()))

    def _attention(self, q, k, v, layout: str = "bshd"):
        c = self.cfg
        # A window covering the whole (or more of the) sequence is
        # mathematically plain causal; normalize to 0 so the dispatch
        # keeps the fused/flash paths (windowed ring blocks run the
        # einsum reference) and skips no-op band masks. The comparison
        # is against the GLOBAL sequence length: inside the pipeline's
        # shard_map with sequence parallelism, q.shape[1] is the local
        # S/sp shard — comparing the window against THAT would turn a
        # valid window silently into full causal.
        S_total = q.shape[2] if layout == "bhsd" else q.shape[1]
        if self._inside_pp and c.attention_impl in ("ring", "ulysses"):
            from distributed_training_tpu.runtime import AXIS_SP
            S_total *= self._mesh_axis_sizes().get(AXIS_SP, 1)
        window = (c.attention_window
                  if 0 < c.attention_window < S_total else 0)
        if c.attention_impl in ("ring", "ulysses"):
            if layout != "bshd":
                raise ValueError(
                    "sequence-parallel attention takes BSHD inputs; "
                    "the BHSD fast path is single-device-flash only")
            if self.mesh is None:
                raise ValueError(
                    f"attention_impl='{c.attention_impl}' requires "
                    "bind_mesh(mesh) before tracing (the Trainer does "
                    "this)")
            if c.attention_impl == "ulysses":
                from distributed_training_tpu.parallel.ulysses import (
                    make_ulysses_attention, ulysses_attention,
                )
                from distributed_training_tpu.runtime import (
                    AXIS_SP, AXIS_TP)
                sizes = self._mesh_axis_sizes()
                tp = sizes.get(AXIS_TP, 1)
                sp = sizes.get(AXIS_SP, 1)
                if self._inside_pp:
                    # Already inside the pipeline's shard_map (every
                    # mesh axis is manual there): call the collective-
                    # level fn directly — a nested shard_map would
                    # throw. Stage params are replicated over tp
                    # (pipeline_spec), so heads arrive whole and only
                    # sp divides them.
                    if c.n_kv_heads % sp or c.n_heads % sp:
                        raise ValueError(
                            f"attention_impl='ulysses' under pp with "
                            f"sp={sp} needs n_heads ({c.n_heads}) and "
                            f"n_kv_heads ({c.n_kv_heads}) divisible "
                            "by sp")
                    return ulysses_attention(
                        q, k, v, axis_name=AXIS_SP, causal=True,
                        block_q=c.flash_block_q,
                        block_k=c.flash_block_k,
                        window=window)
                if c.n_kv_heads % (tp * sp) or c.n_heads % (tp * sp):
                    # Heads are the shard currency for BOTH tp and the
                    # Ulysses a2a — refuse up front with global counts
                    # (the in-shard_map check would report per-shard
                    # numbers).
                    raise ValueError(
                        f"attention_impl='ulysses' on tp={tp}, "
                        f"sp={sp} needs n_heads ({c.n_heads}) and "
                        f"n_kv_heads ({c.n_kv_heads}) divisible by "
                        "tp*sp; use attention_impl='ring' (no head "
                        "constraint)")
                head_ax = AXIS_TP if tp > 1 else None
                fn = make_ulysses_attention(self.mesh, causal=True,
                                            block_q=c.flash_block_q,
                                            block_k=c.flash_block_k,
                                            head_axis=head_ax,
                                            window=window)
                return fn(q, k, v)
            from distributed_training_tpu.parallel.ring_attention import (
                make_ring_attention, ring_attention,
            )
            # (only the ring reaches here — ulysses returned above).
            # attention_window composes: the ring skips blocks behind
            # the window and band-masks the boundary block in GLOBAL
            # positions (parallel/ring_attention.py) — this is the
            # sequence-parallel option for windowed GQA models whose
            # head counts rule out Ulysses (H % (tp·sp) != 0).
            from distributed_training_tpu.runtime import (
                AXIS_SP, AXIS_TP)
            if self._inside_pp:
                # Same pattern as the Ulysses branch: inside the
                # pipeline's shard_map the sp axis is already manual,
                # so call the collective-level ring directly (stage
                # params are replicated over tp there, so no head
                # axis applies).
                return ring_attention(q, k, v, axis_name=AXIS_SP,
                                      causal=True,
                                      block_q=c.flash_block_q,
                                      block_k=c.flash_block_k,
                                      window=window)
            sizes = self._mesh_axis_sizes()
            head_ax = AXIS_TP if sizes.get(AXIS_TP, 1) > 1 else None
            fn = make_ring_attention(self.mesh, causal=True,
                                     head_axis=head_ax,
                                     block_q=c.flash_block_q,
                                     block_k=c.flash_block_k,
                                     window=window)
            return fn(q, k, v)
        # Per-shard flash under a bound multi-device mesh must run
        # inside shard_map: the SPMD partitioner cannot partition a
        # Mosaic custom call ("Mosaic kernels cannot be automatically
        # partitioned"), so the plain-jit path that works single-chip
        # FAILS TO COMPILE on a real pod with dp/fsdp/tp > 1 — caught
        # by the device-less 7B fsdp=16 topology compile (the CPU
        # dryrun masked it: off-TPU the dispatch demotes to naive,
        # which the partitioner handles). Inside the pipeline's
        # shard_map every axis is already manual, so the direct call
        # is correct there.
        if (self.mesh is not None and not self._inside_pp
                and c.attention_impl in ("auto", "flash")
                and self._flash_active(S_total)):
            # _flash_active already returned False for the
            # tp-indivisible case (see _tp_head_shardable) — here the
            # kernel is definitely running, so wrap it in shard_map.
            from distributed_training_tpu.runtime import AXIS_TP
            sizes = self._mesh_axis_sizes()
            b_axes = self._active_batch_axes()
            head_ax = AXIS_TP if sizes.get(AXIS_TP, 1) > 1 else None
            if b_axes or head_ax:
                from jax.experimental.shard_map import shard_map
                from jax.sharding import PartitionSpec as P
                if layout == "bhsd":
                    spec = P(b_axes or None, head_ax, None, None)
                else:
                    spec = P(b_axes or None, None, head_ax, None)
                fn = shard_map(
                    functools.partial(
                        dot_product_attention, causal=True,
                        impl=c.attention_impl,
                        block_q=c.flash_block_q,
                        block_k=c.flash_block_k,
                        window=window, layout=layout),
                    mesh=self.mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_rep=False)
                return fn(q, k, v)
        impl = c.attention_impl
        if impl in ("auto", "flash") and not self._tp_head_shardable():
            # The kernel can't take a fractional tp head shard — run
            # the naive path, which the partitioner handles with
            # collectives (correct, slower; ring attention is the
            # fast option for such head counts). Matches
            # _flash_active, so the remat allow-lists save attn_out.
            impl = "naive"
        return dot_product_attention(q, k, v, causal=True,
                                     impl=impl,
                                     block_q=c.flash_block_q,
                                     block_k=c.flash_block_k,
                                     window=window, layout=layout)

    # -- init --------------------------------------------------------------

    def init(self, rng: jax.Array):
        c = self.cfg
        pdt = jnp.dtype(c.param_dtype)
        keys = iter(jax.random.split(rng, 16))
        std = 0.02
        L, D, F = c.n_layers, c.d_model, c.d_ff
        H, Hkv, hd = c.n_heads, c.n_kv_heads, c.head_dim

        def norm_pair():
            return {"scale": jnp.ones((L, D), pdt),
                    "bias": jnp.zeros((L, D), pdt)}

        params = {
            "tok_embed": normal_init(next(keys), (c.vocab_size, D), std,
                                     pdt),
            "ln1": norm_pair(),
            "ln2": norm_pair(),
            "attn": {
                "wq": normal_init(next(keys), (L, D, H, hd), std, pdt),
                "wk": normal_init(next(keys), (L, D, Hkv, hd), std, pdt),
                "wv": normal_init(next(keys), (L, D, Hkv, hd), std, pdt),
                # GPT-2-style depth-scaled residual-out init.
                "wo": normal_init(next(keys), (L, H, hd, D),
                                  std / (2 * L) ** 0.5, pdt),
            },
            "final_norm": {"scale": jnp.ones((D,), pdt),
                           "bias": jnp.zeros((D,), pdt)},
        }
        if c.moe_num_experts > 0:
            E = c.moe_num_experts
            params["mlp"] = {
                "router": normal_init(next(keys), (L, D, E), std, pdt),
                "wi": normal_init(next(keys), (L, E, D, F), std, pdt),
                "wo": normal_init(next(keys), (L, E, F, D),
                                  std / (2 * L) ** 0.5, pdt),
            }
        else:
            params["mlp"] = {
                "wi": normal_init(next(keys), (L, D, F), std, pdt),
                "bi": jnp.zeros((L, F), pdt),
                "wo": normal_init(next(keys), (L, F, D),
                                  std / (2 * L) ** 0.5, pdt),
                "bo": jnp.zeros((L, D), pdt),
            }
        if c.pos_encoding == "learned":
            params["pos_embed"] = normal_init(
                next(keys), (c.max_seq_len, D), std, pdt)
        if not c.tie_embeddings:
            params["lm_head"] = normal_init(
                next(keys), (D, c.vocab_size), std, pdt)
        return params

    # -- logical sharding axes --------------------------------------------

    def logical_axes(self):
        c = self.cfg
        axes = {
            "tok_embed": ("vocab", "embed"),
            "ln1": {"scale": (None, "embed"), "bias": (None, "embed")},
            "ln2": {"scale": (None, "embed"), "bias": (None, "embed")},
            "attn": {
                "wq": (None, "embed", "heads", None),
                "wk": (None, "embed", "kv", None),
                "wv": (None, "embed", "kv", None),
                "wo": (None, "heads", None, "embed"),
            },
            "final_norm": {"scale": ("embed",), "bias": ("embed",)},
        }
        if c.moe_num_experts > 0:
            axes["mlp"] = {
                "router": (None, "embed", None),
                "wi": (None, "expert", "embed", "mlp"),
                "wo": (None, "expert", "mlp", "embed"),
            }
        else:
            axes["mlp"] = {
                "wi": (None, "embed", "mlp"),
                "bi": (None, "mlp"),
                "wo": (None, "mlp", "embed"),
                "bo": (None, "embed"),
            }
        if c.pos_encoding == "learned":
            axes["pos_embed"] = (None, "embed")
        if not c.tie_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # -- forward -----------------------------------------------------------

    def _block(self, x: jax.Array, layer: dict, positions: jax.Array,
               dropout_rng: jax.Array | None = None,
               return_kv: bool = False):
        """One decoder block. x: (B, S, D) in compute dtype.
        Returns (x, aux_loss) — plus the post-rope (k, v) when
        ``return_kv`` (generation prefill fills its cache from them).
        ``dropout_rng`` non-None enables residual-branch dropout at
        ``cfg.dropout`` (GPT-2's resid_pdrop)."""
        c = self.cfg
        dt = x.dtype
        drop = (functools.partial(_dropout, rate=c.dropout)
                if dropout_rng is not None else None)


        # checkpoint_name tags drive the remat policies (allow-list
        # semantics — save_only_these_names; the "anything except"
        # combinator is defeated by aliasing: it happily saves the
        # producing einsum's output, leaving the name a no-op).
        # "selective" saves only attn_out; "mlp" saves every D-wide
        # tag below and recomputes just the F-wide MLP hiddens.
        name = jax.ad_checkpoint.checkpoint_name

        h = _layer_norm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        h = name(h, "ln1_out")
        # BHSD fast path (single-device flash): the qkv projections
        # emit the kernels' (B, H, S, D) layout directly — XLA folds
        # the output permutation into the matmul — so the flash
        # wrapper's per-layer q/k/v/out transposes (and their remat
        # recompute in backward) vanish. Everything else (ring,
        # ulysses, naive) keeps the BSHD contract.
        bhsd = (not return_kv) and self._bhsd_fast(x.shape[1])
        lay = "bhsk" if bhsd else "bshk"
        q = jnp.einsum(f"bsd,dhk->{lay}", h,
                       self._w(layer["attn"]["wq"], dt, "attn/wq"))
        k = jnp.einsum(f"bsd,dhk->{lay}", h,
                       self._w(layer["attn"]["wk"], dt, "attn/wk"))
        v = jnp.einsum(f"bsd,dhk->{lay}", h,
                       self._w(layer["attn"]["wv"], dt, "attn/wv"))
        if c.pos_encoding == "rope":
            q, k = _rope(q, k, positions,
                         layout="bhsd" if bhsd else "bshd")
        # Post-rope: saving these skips both the qkv einsums and the
        # rope rotation in backward (rope's VJP needs only cos/sin).
        q, k, v = name(q, "q_rope"), name(k, "k_rope"), name(v, "v_proj")
        attn = self._attention(q, k, v,
                               layout="bhsd" if bhsd else "bshd")
        attn = name(attn, "attn_out")
        attn_proj = jnp.einsum(f"{lay},hkd->bsd", attn,
                               self._w(layer["attn"]["wo"], dt,
                                       "attn/wo"))
        if drop is not None:
            attn_proj = drop(attn_proj,
                             rng=jax.random.fold_in(dropout_rng, 0))
        x = name(x + attn_proj, "resid_attn")

        h = _layer_norm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
        h = name(h, "ln2_out")
        if c.moe_num_experts > 0:
            mlp_out, aux = _moe_mlp(h, layer["mlp"], c, w=self._w)
        else:
            m = layer["mlp"]
            # Under the "mlp" policy's allow-list the two (B, S, 4D)
            # tensors here are the only recompute (wi-matmul + gelu in
            # backward); "mlp_pre" saves the tagged pre-gelu one and
            # recomputes just the elementwise gelu.
            u = jnp.einsum(
                "bsd,df->bsf", h, self._w(m["wi"], dt, "mlp/wi")
            ) + m["bi"].astype(dt)
            # Tag is a no-op unless the active policy allow-lists it
            # ("mlp_pre"); under "mlp" both (B, S, 4D) tensors stay
            # un-named and are the policy's deliberate recompute.
            u = name(u, "mlp_pre")
            u = jax.nn.gelu(u)
            mlp_out = jnp.einsum(
                "bsf,fd->bsd", u, self._w(m["wo"], dt, "mlp/wo")
            ) + m["bo"].astype(dt)
            aux = jnp.zeros((), jnp.float32)
        if drop is not None:
            mlp_out = drop(mlp_out,
                           rng=jax.random.fold_in(dropout_rng, 1))
        if return_kv:
            return x + mlp_out, aux, (k, v)
        return x + mlp_out, aux

    def _trunk(self, params, tokens: jax.Array,
               rng: jax.Array | None = None, train: bool = False
               ) -> tuple[jax.Array, jax.Array]:
        """tokens (B, S) → final-norm hidden states (B, S, D) in compute
        dtype, plus the MoE aux-loss scalar. Everything except the
        unembedding projection (the loss path feeds these straight into
        the fused xent head, ops/xent.py)."""
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        B, S = tokens.shape
        dropping = bool(train and c.dropout > 0.0 and rng is not None)
        # Gather-for-compute (when bound): constrain the TABLE before
        # indexing, so a vocab-sharded embedding is all-gathered once
        # (param-scale, bf16) instead of the lookup emitting an
        # activation-scale (B, S, D) all-reduce of one-hot partials.
        # _gathered_table extends the same discipline to EVERY sharded
        # strategy: this lookup is the MULTICHIP_r05 reshard cliff
        # (SPMD001), fixed by constraining the table, not the output.
        x = self._gathered_table(
            self._w(params["tok_embed"], dt, "tok_embed"))[tokens]
        positions = jnp.arange(S)
        if c.pos_encoding == "learned":
            x = x + self._w(params["pos_embed"], dt,
                            "pos_embed")[:S]
        if dropping:  # GPT-2's embd_pdrop (fold_in needs non-negative)
            x = _dropout(x, rng=jax.random.fold_in(rng, 1_000_003),
                         rate=c.dropout)

        # Stack per-layer params for the scan: they already carry a
        # leading L dim.
        stacked = {k: params[k] for k in ("ln1", "ln2", "attn", "mlp")}

        pp = self._mesh_axis_sizes().get("pp", 1)

        # Per-layer dropout rngs derive from (global layer id,
        # microbatch index, data-shard index) so the draws are identical
        # on every schedule: plain scan uses mb=0/shard=0; the pipeline
        # threads the tick's microbatch through and folds the batch
        # shard (inside shard_map each device sees only its batch rows,
        # so without the shard term every dp/fsdp shard would draw the
        # SAME mask — correlated dropout across data shards). pp=N with
        # one microbatch and one data shard draws exactly the masks
        # pp=1 draws (tested in tests/test_pipeline.py). Carve-out:
        # under pp>1 WITH sp>1 the sp index is folded in too (each sp
        # member holds a sequence slice and draws its own local mask),
        # so masks are decorrelated along S but do NOT bit-match the
        # pp=1 global draw — same objective in distribution, different
        # realization; cross-layout trajectory parity with dropout>0
        # holds only at sp=1.
        rng7 = jax.random.fold_in(rng, 7) if dropping else None

        def body_with(mb_idx, shard_idx, pos=None):
            pos = positions if pos is None else pos

            def body(carry, inp):
                layer, lid = inp
                x, aux = carry
                lrng = None
                if dropping:
                    lrng = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.fold_in(rng7, lid), mb_idx),
                        shard_idx)
                x, layer_aux = self._block(x, layer, pos,
                                           dropout_rng=lrng)
                return (x, aux + layer_aux), None
            return body

        layer_ids_all = jnp.arange(c.n_layers, dtype=jnp.int32)

        if pp > 1:
            # Pipeline wavefront over pp stages (parallel/pipeline.py):
            # each stage scans its local layer chunk per microbatch.
            # Both sequence-parallel impls compose: the stage body
            # calls the collective-level attention directly — see
            # _attention (inside the pipeline shard_map every mesh
            # axis is manual; a nested shard_map would throw).
            from distributed_training_tpu.parallel.pipeline import (
                pipeline_apply,
            )
            from distributed_training_tpu.runtime import (
                AXIS_SP, BATCH_AXES)

            sp = self._mesh_axis_sizes().get(AXIS_SP, 1)
            seq_parallel = (c.attention_impl in ("ring", "ulysses")
                            and sp > 1)
            batch_ax = tuple(
                a for a in BATCH_AXES
                if self._mesh_axis_sizes().get(a, 1) > 1)

            def stage_body(stage_params, layer_ids, xb, mb_idx):
                shard_idx = (jax.lax.axis_index(batch_ax) if batch_ax
                             else jnp.zeros((), jnp.int32))
                pos = None
                if seq_parallel:
                    # Fold the sp position in too: each sp member
                    # holds a different sequence slice, and without
                    # this term they would all draw the SAME local
                    # dropout mask (correlated dropout along S).
                    shard_idx = (shard_idx * sp
                                 + jax.lax.axis_index(AXIS_SP))
                    # And offset positions to the shard's slice of the
                    # global sequence (rope must see global indices).
                    s_loc = xb.shape[1]
                    pos = (jax.lax.axis_index(AXIS_SP) * s_loc
                           + jnp.arange(s_loc))
                # The sweep's scan_unroll knob applies here too; the
                # stage's local layer count (L/pp, or L/(v*pp) per
                # interleaved chunk) must divide it, else fall back
                # loudly rather than silently ignoring the knob.
                l_local = jax.tree.leaves(stage_params)[0].shape[0]
                unroll = c.scan_unroll
                if unroll > 1 and l_local % unroll:
                    warnings.warn(
                        f"scan_unroll={unroll} does not divide the "
                        f"pipeline stage's {l_local} local layers; "
                        "using unroll=1", stacklevel=2)
                    unroll = 1
                (xb, aux), _ = jax.lax.scan(
                    body_with(mb_idx, shard_idx, pos=pos),
                    (xb, jnp.zeros((), jnp.float32)),
                    (stage_params, layer_ids), unroll=unroll)
                return xb, aux

            # Largest microbatch count <= pp_microbatches such that the
            # per-microbatch batch B/M still splits evenly over the
            # data-sharded mesh axes (shard_map requires it).
            shards = math.prod(
                self._mesh_axis_sizes().get(a, 1) for a in BATCH_AXES)
            M = max(m for m in range(1, min(c.pp_microbatches, B) + 1)
                    if B % m == 0 and (B // m) % shards == 0)
            self._inside_pp = True
            try:
                x, aux = pipeline_apply(
                    stage_body, stacked, x, self.mesh,
                    num_microbatches=M, batch_axes=BATCH_AXES,
                    schedule=c.pp_schedule,
                    virtual_stages=c.pp_virtual_stages,
                    seq_axis=AXIS_SP if seq_parallel else None)
            finally:
                self._inside_pp = False
            # aux is an intensive (batch-mean) statistic summed over M
            # microbatches — renormalize so pp meshes optimize the same
            # objective as non-pp meshes.
            aux = aux / M
        else:
            block = body_with(jnp.zeros((), jnp.int32),
                              jnp.zeros((), jnp.int32))
            if c.remat:
                # Values validated in __post_init__; "full" → default
                # save-nothing policy. Allow-lists only: see the
                # checkpoint_name comment in _block. The attention
                # output exists under two names — attn_out (BSHD, the
                # model-side tag) and flash_out (BHSD, the kernel's
                # custom-VJP residual) — and saving both would store
                # the same values twice (~B*S*D*2 bytes/layer). Save
                # whichever layout the active backward actually
                # consumes: flash's VJP needs its own residuals (the
                # BSHD twin is then one cheap transpose away), the
                # naive path has no flash residuals at all.
                if self._flash_active(x.shape[1]):
                    attn_names = FLASH_RESIDUAL_NAMES
                else:
                    attn_names = ("attn_out",)
                if c.remat_policy == "selective":
                    policy = (jax.checkpoint_policies
                              .save_only_these_names(*attn_names))
                elif c.remat_policy in ("mlp", "mlp_pre"):
                    # The "mlp_pre" tag exists only in the dense MLP
                    # branch; with MoE active the policy degrades to
                    # "mlp" (an unmatched allow-list name is a silent
                    # no-op — keep the estimator in utils/memory.py in
                    # agreement).
                    base = (MLP_PRE_POLICY_SAVED
                            if (c.remat_policy == "mlp_pre"
                                and c.moe_num_experts == 0)
                            else MLP_POLICY_SAVED)
                    saved = tuple(
                        n for n in base
                        if n not in ("attn_out", *FLASH_RESIDUAL_NAMES)
                    ) + attn_names
                    policy = (jax.checkpoint_policies
                              .save_only_these_names(*saved))
                else:
                    policy = None
                block = jax.checkpoint(block, prevent_cse=False,
                                       policy=policy)

            def pinned_block(carry, inp, _block=block):
                # Batch-pin OUTSIDE the checkpoint boundary so the
                # residual jax.checkpoint saves (its input) is the
                # batch-sharded value — see _pin_batch.
                xc, acc = carry
                return _block((self._pin_batch(xc), acc), inp)

            (x, aux), _ = jax.lax.scan(
                pinned_block, (x, jnp.zeros((), jnp.float32)),
                (stacked, layer_ids_all), unroll=c.scan_unroll)
        aux = aux / c.n_layers  # mean load-balancing loss over layers

        x = _layer_norm(x, params["final_norm"]["scale"],
                        params["final_norm"]["bias"])
        return x, aux

    def _head(self, params) -> jax.Array:
        """Unembedding matrix (D, V) in param dtype."""
        return (params["tok_embed"].T if self.cfg.tie_embeddings
                else params["lm_head"])

    def apply(self, params, tokens: jax.Array,
              rng: jax.Array | None = None, train: bool = False
              ) -> tuple[jax.Array, jax.Array]:
        """tokens (B, S) int32 → logits (B, S, V) fp32, aux loss scalar.

        Dropout (``cfg.dropout > 0``) is active only when ``train`` and
        an ``rng`` is given; eval/inference is deterministic."""
        x, aux = self._trunk(params, tokens, rng=rng, train=train)
        logits = jnp.einsum("bsd,dv->bsv", x,
                            self._w(self._head(params), x.dtype,
                                    "head"))
        return logits.astype(jnp.float32), aux

    # -- loss --------------------------------------------------------------

    def loss(self, params, batch, rng: jax.Array, train: bool = True):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        if self.cfg.loss_impl == "fused":
            from distributed_training_tpu.ops.xent import lm_cross_entropy
            x, aux = self._trunk(params, inputs, rng=rng, train=train)
            nll = lm_cross_entropy(
                x, self._w(self._head(params), x.dtype, "head"),
                targets, chunk_rows=self.cfg.xent_chunk_rows)
            # Negative target ids are masked pad positions (zero nll &
            # gradient inside the op) — average over real tokens only.
            valid = jnp.sum(targets >= 0)
            loss = jnp.sum(nll) / jnp.maximum(valid, 1)
        else:
            logits, aux = self.apply(params, inputs, rng=rng, train=train)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, jnp.maximum(targets, 0)[..., None],
                axis=-1)[..., 0]
            # Same masking contract as the fused path: negative target
            # ids are pad positions with zero loss contribution.
            nll = jnp.where(targets >= 0, nll, 0.0)
            valid = jnp.sum(targets >= 0)
            loss = jnp.sum(nll) / jnp.maximum(valid, 1)
        metrics = {"loss": loss, "perplexity": jnp.exp(loss)}
        if self.cfg.moe_num_experts > 0:
            loss = loss + self.cfg.moe_aux_weight * aux
            metrics["moe_aux"] = aux
        return loss, metrics

    # -- accounting --------------------------------------------------------

    def num_params(self) -> int:
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        import numpy as np
        return int(sum(np.prod(s.shape) for s in jax.tree.leaves(shapes)))

    def flops_per_token(self, seq_len: int | None = None) -> float:
        """Fwd+bwd FLOPs/token: 6 * N_dense + attention quadratic term
        (causal → half; sliding window → the band's average width), the
        standard PaLM-appendix accounting."""
        c = self.cfg
        S = seq_len or c.max_seq_len
        N = self.num_params()
        if c.moe_num_experts > 0:
            # only top_k experts execute per token
            expert_p = (c.moe_num_experts * 2 * c.d_model * c.d_ff
                        * c.n_layers)
            N = N - expert_p + expert_p * c.moe_top_k // c.moe_num_experts
        # Average live keys per query: causal = (S+1)/2 ~ S/2; with a
        # window W, query i sees min(i+1, W) keys.
        if c.attention_window:
            W = min(c.attention_window, S)
            avg_keys = W - W * (W - 1) / (2 * S)
        else:
            avg_keys = S * 0.5
        attn = 12 * c.n_layers * c.d_model * avg_keys
        return 6.0 * N + attn

    def flops_per_sample(self) -> float:
        # Trainer feeds (seq_len + 1) token rows; model consumes seq_len.
        S = self.cfg.max_seq_len
        return self.flops_per_token(S) * S

    # -- generation --------------------------------------------------------

    def _decode_cache_len(self, max_len: int) -> int:
        """KV-cache sequence capacity for decode: a sliding window
        needs only the last ``window`` positions (the rolling buffer —
        O(window) decode memory instead of O(max_len)); full causal
        keeps every position."""
        c = self.cfg
        if c.attention_window:
            return min(max_len, c.attention_window)
        return max_len

    def _attend_cache(self, q, k_cache, v_cache, pos):
        """Single-position attention: q (B, 1, H, hd) against the cache
        (B, Sm, Hkv, hd). GQA-grouped like ops.attention (hkv-major
        head order).

        The cache is a MODULAR ring over absolute positions: position
        p lives in slot ``p % Sm``, so slot s currently holds absolute
        position ``pos − ((pos − s) mod Sm)`` — for a full-length
        cache (Sm > pos) that reduces to s itself for s ≤ pos and a
        negative (masked) value beyond it, and for a window-sized
        rolling buffer it is the newest ≤ pos occupant of the slot.
        One mask therefore covers both layouts: visible iff the slot's
        absolute position is ≥ 0 (ever written) and inside the
        attention window when one is set."""
        c = self.cfg
        group = c.n_heads // c.n_kv_heads
        B, Sm = k_cache.shape[0], k_cache.shape[1]
        qg = q[:, 0].reshape(B, c.n_kv_heads, group, c.head_dim)
        logits = jnp.einsum(
            "bhgd,bshd->bhgs", qg, k_cache,
            preferred_element_type=jnp.float32) * c.head_dim ** -0.5
        idx = jnp.arange(Sm)[None, None, None, :]
        abs_pos = pos - ((pos - idx) % Sm)
        mask = abs_pos >= 0
        if c.attention_window:
            mask = jnp.logical_and(
                mask, abs_pos >= pos - (c.attention_window - 1))
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhgs,bshd->bhgd",
                         probs.astype(v_cache.dtype), v_cache,
                         preferred_element_type=jnp.float32)
        return out.reshape(B, 1, c.n_heads, c.head_dim).astype(q.dtype)

    def _block_decode(self, x, layer, k_cache, v_cache, pos):
        """One block for one new token at position ``pos`` (B, 1, D),
        reading/extending the layer's KV cache."""
        c = self.cfg
        dt = x.dtype
        h = _layer_norm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q = jnp.einsum("bsd,dhk->bshk", h, layer["attn"]["wq"].astype(dt))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["attn"]["wk"].astype(dt))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["attn"]["wv"].astype(dt))
        if c.pos_encoding == "rope":
            q, k = _rope(q, k, jnp.full((1,), pos, jnp.int32))
        # Modular slot: identity for a full-length cache, ring-wrap
        # for the window-sized rolling buffer (see _attend_cache).
        slot = pos % k_cache.shape[1]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0))
        attn = self._attend_cache(q, k_cache, v_cache, pos)
        x = x + jnp.einsum("bshk,hkd->bsd", attn,
                           layer["attn"]["wo"].astype(dt))
        h = _layer_norm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
        if c.moe_num_experts > 0:
            mlp_out, _ = _moe_mlp(h, layer["mlp"], c)
        else:
            m = layer["mlp"]
            u = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h,
                                       m["wi"].astype(dt))
                            + m["bi"].astype(dt))
            mlp_out = jnp.einsum(
                "bsf,fd->bsd", u, m["wo"].astype(dt)
            ) + m["bo"].astype(dt)
        return x + mlp_out, k_cache, v_cache

    def _lm_head(self, params, x_last):
        """(B, D) hidden → (B, V) fp32 logits (final LN + head)."""
        x = _layer_norm(x_last, params["final_norm"]["scale"],
                        params["final_norm"]["bias"])
        head = (params["tok_embed"].T if self.cfg.tie_embeddings
                else params["lm_head"])
        return jnp.einsum("bd,dv->bv", x,
                          head.astype(x.dtype)).astype(jnp.float32)

    def prefill(self, params, tokens, max_len: int):
        """Run the prompt (B, P) through the stack, returning per-layer
        KV caches plus fp32 logits for the next position:
        (k_cache (L,B,Sm,Hkv,hd), v_cache, logits), where
        ``Sm = _decode_cache_len(max_len)`` — ``max_len`` for full
        causal, the window size for windowed models (the rolling
        ring-slot layout _attend_cache reads; position p lives in slot
        ``p % Sm``)."""
        c = self.cfg
        dt = jnp.dtype(c.dtype)
        B, P = tokens.shape
        x = params["tok_embed"][tokens].astype(dt)
        positions = jnp.arange(P)
        if c.pos_encoding == "learned":
            x = x + params["pos_embed"][:P].astype(dt)
        stacked = {k: params[k] for k in ("ln1", "ln2", "attn", "mlp")}

        def body(carry, layer):
            x, = carry
            x, _aux, kv = self._block(x, layer, positions,
                                      return_kv=True)
            return (x,), kv

        (x,), (ks, vs) = jax.lax.scan(body, (x,), stacked)
        # ks: (L, B, P, Hkv, hd) → caches of capacity Sm. Windowed
        # decode keeps only the last min(P, Sm) prompt positions, each
        # in its modular slot p % Sm (slots hit at most once — the kept
        # positions are consecutive), matching _attend_cache's ring
        # layout; a full-length cache gets the identity layout (slot p
        # == p) plus zero padding.
        Sm = self._decode_cache_len(max_len)
        keep = min(P, Sm)
        zshape = (c.n_layers, B, Sm) + ks.shape[3:]
        slots = (jnp.arange(P - keep, P) % Sm).astype(jnp.int32)
        k_cache = jnp.zeros(zshape, dt).at[:, :, slots].set(
            ks[:, :, P - keep:].astype(dt))
        v_cache = jnp.zeros(zshape, dt).at[:, :, slots].set(
            vs[:, :, P - keep:].astype(dt))
        return k_cache, v_cache, self._lm_head(params, x[:, -1])

    def generate(self, params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 rng: jax.Array | None = None,
                 max_len: int | None = None) -> jax.Array:
        """Autoregressive sampling: (B, P) int32 prompt → (B,
        max_new_tokens) continuations. ``temperature == 0`` is greedy;
        otherwise categorical sampling, optionally truncated to the
        ``top_k`` most likely tokens. The whole loop (prefill + cached
        decode scan) is jitted; no data-dependent Python control flow.
        """
        c = self.cfg
        B, P = prompt.shape
        max_len = max_len or c.max_seq_len
        if P + max_new_tokens > max_len:
            raise ValueError(
                f"prompt ({P}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len ({max_len})")
        if temperature > 0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs an rng")
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # The compiled loop is cached per trace signature — a bare
        # jax.jit(run) here would retrace and recompile on EVERY call.
        cache_key = (P, max_new_tokens, temperature, top_k, max_len)
        if not hasattr(self, "_generate_cache"):
            self._generate_cache: dict = {}
        cached = self._generate_cache.get(cache_key)
        if cached is not None:
            return cached(params, prompt, rng)
        stacked_keys = ("ln1", "ln2", "attn", "mlp")

        def sample(logits, key):
            if temperature <= 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / temperature
            if top_k:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(key, logits).astype(jnp.int32)

        def run(params, prompt, rng):
            k_cache, v_cache, logits = self.prefill(params, prompt,
                                                    max_len)
            stacked = {k: params[k] for k in stacked_keys}
            rng0, rng_loop = jax.random.split(rng)
            tok0 = sample(logits, rng0)

            def step(carry, i):
                k_cache, v_cache, tok, key = carry
                pos = P + i
                x = params["tok_embed"][tok][:, None, :].astype(
                    jnp.dtype(c.dtype))
                if c.pos_encoding == "learned":
                    x = x + params["pos_embed"][pos][
                        None, None, :
                    ].astype(x.dtype)

                def layer_body(xc, inp):
                    layer, kc, vc = inp
                    x, = xc
                    x, kc, vc = self._block_decode(x, layer, kc, vc,
                                                   pos)
                    return (x,), (kc, vc)

                (x,), (k_cache, v_cache) = jax.lax.scan(
                    layer_body, (x,), (stacked, k_cache, v_cache))
                logits = self._lm_head(params, x[:, 0])
                key, sub = jax.random.split(key)
                nxt = sample(logits, sub)
                return (k_cache, v_cache, nxt, key), nxt

            n_scan = max_new_tokens - 1
            if n_scan > 0:
                (_, _, _, _), rest = jax.lax.scan(
                    step, (k_cache, v_cache, tok0, rng_loop),
                    jnp.arange(n_scan))
                return jnp.concatenate(
                    [tok0[:, None], rest.T.astype(jnp.int32)], axis=1)
            return tok0[:, None]

        fn = jax.jit(run)
        self._generate_cache[cache_key] = fn
        return fn(params, prompt, rng)


def _cast_w(p, dt, path=None):
    """Default weight consumer for the MoE helpers: plain cast. The
    train path passes ``Transformer._w`` instead so expert/router
    weights get the FSDP gather-for-compute constraint (without it,
    fsdp-sharded expert weights re-trigger the activation-all-reduce
    pathology benchmarks/audit_collectives.py exposed)."""
    return p.astype(dt)


def _topk_by_argmax(p: jax.Array, k: int):
    """Top-k along the last axis via k iterations of argmax + mask.

    Identical selection, ordering AND gradient to ``jax.lax.top_k``
    (descending values, first-index tie-break; cotangent scattered
    only to the selected indices), but it lowers to plain reduces and
    gathers over the UNSHARDED expert axis — lax.top_k becomes a TopK
    custom-call the SPMD partitioner cannot partition, so it
    all-gathered the full (B, G, gs, E) routing probs across data-
    parallel shards before routing (the one activation-scale
    collective in the otherwise-clean MoE communication contract,
    BENCH_r04; now pinned to zero by
    tests/test_benchmarks.py::test_fsdp_step_has_no_activation_scale_collectives).
    k is the tiny moe_top_k (1-2 in practice), so the unrolled loop
    costs k cheap (…, E) passes. Values are re-gathered from the
    ORIGINAL tensor via take_along_axis — jnp.max's VJP would split
    the cotangent across tied maxima (e.g. a freshly-initialized
    router where every expert ties), leaking gradient onto unselected
    experts."""
    orig = p
    vals, idxs = [], []
    for _ in range(k):
        i = jnp.argmax(p, axis=-1)
        vals.append(jnp.take_along_axis(orig, i[..., None],
                                        axis=-1)[..., 0])
        idxs.append(i)
        p = jnp.where(jax.nn.one_hot(i, p.shape[-1], dtype=jnp.bool_),
                      -jnp.inf, p)
    return jnp.stack(vals, -1), jnp.stack(idxs, -1)


def _moe_router(h: jax.Array, mlp: dict, c: TransformerConfig,
                valid: jax.Array | None = None, w=_cast_w):
    """Shared routing head: normalized top-k weights/indices + the
    Switch/GShard load-balancing aux (E · Σ_e mean_prob_e · mean_frac_e),
    computed pre-capacity so the balance signal sees dropped tokens.

    ``valid`` (same shape as h minus the feature dim) masks padding
    rows: they are removed from the assignment one-hots (so they claim
    no capacity slots) and from the aux statistics."""
    dt = h.dtype
    E, k = c.moe_num_experts, c.moe_top_k
    gates = jnp.einsum("...d,de->...e", h,
                       w(mlp["router"], dt, "mlp/router"))
    probs = jax.nn.softmax(gates.astype(jnp.float32), axis=-1)
    topv, topi = _topk_by_argmax(probs, k)            # (..., k)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(topi, E, dtype=jnp.float32)  # (..., k, E)
    red = tuple(range(probs.ndim - 1))
    if valid is None:
        frac = jnp.mean(jnp.sum(onehot, axis=-2), axis=red)  # (E,)
        mean_prob = jnp.mean(probs, axis=red)                # (E,)
    else:
        v = valid.astype(jnp.float32)
        onehot = onehot * v[..., None, None]
        n = jnp.maximum(jnp.sum(v), 1.0)
        frac = jnp.sum(onehot, axis=red + (onehot.ndim - 2,)) / n
        mean_prob = jnp.sum(probs * v[..., None], axis=red) / n
    aux = E * jnp.sum(frac * mean_prob)
    return topv, onehot, aux


def _moe_mlp_dense(h, mlp, c: TransformerConfig, w=_cast_w):
    """Reference dispatch: every expert computes every token, masked
    combine. Exact but O(E) FLOPs — numerics baseline for the routed
    path and the sane choice for very small E."""
    dt = h.dtype
    topv, onehot, aux = _moe_router(h, mlp, c, w=w)
    combine = jnp.einsum("bsk,bske->bse", topv, onehot)  # (B,S,E)
    up = jnp.einsum("bsd,edf->besf", h, w(mlp["wi"], dt, "mlp/wi"))
    # Deliberately un-named: under remat_policy="mlp"'s allow-list the
    # (B, E, S, F) expert hiddens (E× the dense class) are recomputed.
    up = jax.nn.gelu(up)
    down = jnp.einsum("besf,efd->besd", up,
                      w(mlp["wo"], dt, "mlp/wo"))
    out = jnp.einsum("besd,bse->bsd", down, combine.astype(dt))
    return out, aux


def _moe_group_size(S: int, cap: int) -> tuple[int, int]:
    """Routing-group length along the SEQUENCE axis and the padded
    sequence length: S pads UP to a multiple of ``min(S, cap)`` rather
    than shrinking the group to a divisor — a divisor search would
    collapse to tiny groups for poorly-composite lengths (e.g. 1031),
    exploding the per-group capacity overhead. Pad positions are
    masked out of routing entirely."""
    g = min(S, max(1, cap))
    return g, -(-S // g) * g


def _moe_mlp_routed(h, mlp, c: TransformerConfig, w=_cast_w):
    """Capacity-bounded top-k dispatch (GShard-style, TPU-first).

    Groups are SEQUENCE chunks within each batch row — the batch axis
    is never flattened into the group axis, so a dp/fsdp-sharded
    batch stays shard-local through routing and dispatch (the same
    sharding contract as ops/xent.py; an earlier version grouped
    flat (B*S) tokens, which made the SPMD partitioner gather
    routing tensors across data-parallel ranks —
    benchmarks/audit_collectives.py). Each (row, group) routes its
    ``gs`` tokens into per-expert capacity buffers
    ``C = ceil(cf * k * gs / E)``: position-in-expert comes from a
    slot-major cumsum (slot 0 beats slot 1 on overflow — earlier/
    higher top-k choices win buffer slots), overflowing tokens are
    dropped (their combine weight never lands in a buffer slot,
    standard GShard semantics). Dispatch/combine are one-hot einsums
    — pure MXU work that shards over the ``expert`` axis under EP —
    and expert FLOPs are ``4*D*F*cf*k*T``: independent of E at fixed
    top_k, vs the dense path's O(E). Grouping bounds the (gs, E, C)
    dispatch tensor and the dispatch-einsum FLOPs, which would
    otherwise rival the expert compute itself at large T.
    """
    dt = h.dtype
    E, k = c.moe_num_experts, c.moe_top_k
    B, S, D = h.shape
    gs, S_pad = _moe_group_size(S, c.moe_group_size)
    G = S_pad // gs
    C = int(-(-c.moe_capacity_factor * k * gs // E))  # ceil
    C = min(C, gs * k)  # can't hold more than every (token, slot)

    x = h
    valid = None
    if S_pad != S:
        x = jnp.concatenate(
            [x, jnp.zeros((B, S_pad - S, D), x.dtype)], axis=1)
        valid = jnp.broadcast_to(
            jnp.arange(S_pad) < S, (B, S_pad)).reshape(B, G, gs)
    x = x.reshape(B, G, gs, D)
    topv, onehot, aux = _moe_router(x, mlp, c, valid=valid, w=w)
    # (B, G, gs, k, E) -> slot-major (B, G, k*gs, E): all slot-0 rows
    # first, so the running count gives slot 0 strictly higher buffer
    # priority.
    oh = onehot.transpose(0, 1, 3, 2, 4).reshape(B, G, k * gs, E)
    pos = (jnp.cumsum(oh, axis=2) * oh - 1.0).astype(
        jnp.int32
    )                                                 # (B, G, k*gs, E)
    # one_hot maps out-of-range indices to the zero vector, which IS
    # the drop: unselected entries (pos == -1) and capacity overflow
    # (pos >= C) land in no buffer slot.
    slot = jax.nn.one_hot(pos, C, dtype=jnp.float32)  # (B,G,k*gs,E,C)
    wts = topv.transpose(0, 1, 3, 2).reshape(B, G, k * gs)
    combine = (jnp.einsum("bgt,bgtec->bgtec", wts, slot)
               .reshape(B, G, k, gs, E, C)
               .sum(axis=2))                          # (B, G, gs, E, C)
    dispatch = combine > 0.0

    expert_in = jnp.einsum("bgsec,bgsd->bgecd", dispatch.astype(dt), x)
    up = jnp.einsum("bgecd,edf->bgecf", expert_in,
                    w(mlp["wi"], dt, "mlp/wi"))
    # Deliberately un-named: under remat_policy="mlp"'s allow-list the
    # (B, G, E, C, F) expert hiddens — the routed path's biggest
    # residuals — are recomputed in backward.
    up = jax.nn.gelu(up)
    down = jnp.einsum("bgecf,efd->bgecd", up,
                      w(mlp["wo"], dt, "mlp/wo"))
    out = jnp.einsum("bgsec,bgecd->bgsd", combine.astype(dt), down)
    return out.reshape(B, S_pad, D)[:, :S], aux


def _moe_mlp(h: jax.Array, mlp: dict, c: TransformerConfig,
             w=_cast_w) -> tuple[jax.Array, jax.Array]:
    """Top-k routed expert MLP; dispatch per ``cfg.moe_impl``."""
    if c.moe_impl == "routed":
        return _moe_mlp_routed(h, mlp, c, w=w)
    return _moe_mlp_dense(h, mlp, c, w=w)


def build_transformer(name: str, loss: str = "auto",
                      dtype: str = "bfloat16", **kwargs) -> Transformer:
    """Build from a preset name or raw kwargs (registry entrypoint)."""
    preset: dict = {}
    if name in PRESETS:
        preset = dict(PRESETS[name])
    elif name == "moe_transformer":
        preset = dict(d_model=512, n_layers=8, n_heads=8,
                      max_seq_len=512, moe_num_experts=8)
    preset.update(kwargs)
    preset.setdefault("dtype", dtype)
    if loss != "auto":
        preset["loss_name"] = loss
    return Transformer(TransformerConfig(**preset))
