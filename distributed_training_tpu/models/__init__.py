"""Model zoo: plain-pytree functional models with logical sharding axes.

The reference's "model zoo" is a single ``torch.nn.Linear`` built inline
(src/distributed_trainer.py:199; playground: ddp_script.py:16-23). The
framework generalizes to the BASELINE.json families — MLP, ResNet-18,
GPT-2-class transformers (125M → 7B) — as *functional* models: explicit
``init(rng) -> params`` pytrees and pure ``apply``/``loss`` functions.
No module framework in the hot path: params are transparent pytrees that
strategies annotate with logical axes and jit shards — the idiomatic
SPMD shape for XLA.
"""

from distributed_training_tpu.models.base import Model  # noqa: F401
from distributed_training_tpu.models.registry import build_model  # noqa: F401
