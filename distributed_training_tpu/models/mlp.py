"""MLP family — the reference parity model.

Covers both reference models: ``Linear(20, 1)`` (src/distributed_trainer.py:
199, conf/model/default.yaml) and the playground's ``SimpleModel`` =
``Linear(10, 1)`` (src/playground/ddp_script.py:16-23), generalized to an
optional ReLU-hidden stack. Losses:

- ``mse``: playground parity (ddp_script.py:135,146) — the task that
  actually learns;
- ``prob_xent``: exact semantics of the reference default trainer's
  ``F.cross_entropy(logits, float_targets)`` over ``output_size`` logits
  — for ``output_size=1`` this is the degenerate gradient-free loss the
  reference ships (SURVEY.md §8 B5), reproduced for parity testing;
- ``xent``: integer-label cross entropy (the non-degenerate variant).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar

import jax
import jax.numpy as jnp

from distributed_training_tpu.models.base import uniform_fan_in


@dataclass
class MLP:
    input_size: int = 20
    output_size: int = 1
    hidden_sizes: list[int] = field(default_factory=list)
    loss_name: str = "mse"
    dtype: str = "float32"
    # Batch keys loss() consumes — trainers validate the dataset
    # against this before jit so a model/dataset mismatch fails with a
    # config-level message, not a KeyError inside the traced step.
    # ClassVar: a contract of loss(), not a constructor hyperparameter.
    batch_keys: ClassVar[tuple[str, ...]] = ("x", "y")

    @property
    def _dims(self) -> list[tuple[int, int]]:
        dims = ([self.input_size] + list(self.hidden_sizes)
                + [self.output_size])
        return list(zip(dims[:-1], dims[1:]))

    def init(self, rng: jax.Array):
        params = {}
        for i, (fan_in, fan_out) in enumerate(self._dims):
            rng, wk, bk = jax.random.split(rng, 3)
            params[f"layer{i}"] = {
                # torch Linear stores (out, in); we store (in, out) for
                # row-major x @ W — same init family either way.
                "w": uniform_fan_in(wk, (fan_in, fan_out), fan_in),
                "b": uniform_fan_in(bk, (fan_out,), fan_in),
            }
        return params

    def apply(self, params, x: jax.Array) -> jax.Array:
        h = x.astype(jnp.dtype(self.dtype))
        n = len(self._dims)
        for i in range(n):
            lyr = params[f"layer{i}"]
            h = h @ lyr["w"].astype(h.dtype) + lyr["b"].astype(h.dtype)
            if i < n - 1:
                h = jax.nn.relu(h)
        return h

    def loss(self, params, batch, rng: jax.Array, train: bool = True):
        del rng, train
        pred = self.apply(params, batch["x"]).astype(jnp.float32)
        y = batch["y"]
        if self.loss_name == "mse":
            loss = jnp.mean((pred - y) ** 2)
        elif self.loss_name == "prob_xent":
            # F.cross_entropy with probability-mode float targets:
            # -sum_c target_c * log_softmax(pred)_c, batch-meaned. With one
            # logit log_softmax ≡ 0 → loss ≡ 0 (reference B5, preserved).
            loss = jnp.mean(
                -jnp.sum(y * jax.nn.log_softmax(pred, axis=-1), axis=-1))
        elif self.loss_name == "xent":
            labels = y.astype(jnp.int32).reshape(-1)
            loss = jnp.mean(
                -jnp.take_along_axis(
                    jax.nn.log_softmax(pred, axis=-1),
                    labels[:, None], axis=-1))
        else:
            raise ValueError(f"unknown loss '{self.loss_name}'")
        return loss, {"loss": loss}

    def logical_axes(self):
        axes = {}
        for i, _ in enumerate(self._dims):
            axes[f"layer{i}"] = {"w": ("embed", "mlp"), "b": ("mlp",)}
        return axes

    def flops_per_sample(self) -> float:
        # fwd+bwd ≈ 3 × (2 × flops of fwd matmuls)
        fwd = sum(2 * a * b for a, b in self._dims)
        return 3.0 * fwd
