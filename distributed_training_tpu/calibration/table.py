"""Calibration tables: fingerprinted measured-hardware curves.

A table holds the measured points ``benchmarks/calibrate.py``
produced for one ``device_kind`` — per-collective ``(accounted_bytes,
seconds)`` curves and a ``(flops, flops_per_s)`` achievable-matmul
curve — plus enough provenance (platform, device count, backend
versions) to judge whether it still describes the hardware. The
committed artifact lives at ``conf/calibration/<chip>.json``.

Conventions (shared with the planner's comms accounting — the table
exists to be evaluated on exactly the bytes ``score_candidate``
counts):

- ``all-gather``: x = bytes of the full gathered tensor;
- ``reduce-scatter``: x = bytes of the full reduced+scattered tensor;
- ``all-reduce``: x = 2x the tensor bytes (the ring's reduce-scatter
  + all-gather phases — the planner's ``2 * P`` convention);
- ``ppermute``: x = bytes each device ships per step through its
  permute links.

Interpolation is piecewise-linear between measured points: below the
smallest point the smallest point's time is the LATENCY FLOOR (a
1-byte collective does not get faster than the wire's round trip);
above the largest point the tail segment's bandwidth extrapolates.
The matmul curve is clamped at both ends (achievable FLOPs saturate).

Integrity mirrors the plan-artifact discipline (``parallel/
planner.py``): a sha256 fingerprint over the canonical body, verified
at load — a hand-edited table refuses to load rather than silently
re-ranking every plan built from it.

Stdlib-only by design: the planner gate, launchers, and targets
registry read tables without importing jax.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import NamedTuple

SCHEMA = 1

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CALIB_DIR = os.path.join(REPO, "conf", "calibration")

# The collective kinds the planner's comms model prices — exactly the
# set benchmarks/calibrate.py measures.
COLLECTIVE_KINDS = ("all-gather", "reduce-scatter", "all-reduce",
                    "ppermute")

# device_kind -> committed-file slug. "TPU v5 lite" and "v5e" are the
# same silicon (utils/metrics.py's substring-matching lesson); longest
# key first so "v5 lite" wins before a hypothetical "v5".
_SLUGS = {
    "v5 lite": "v5e",
    "v5litepod": "v5e",
    "v5e": "v5e",
    "v5p": "v5p",
    "v6e": "v6e",
    "v6 lite": "v6e",
    "v4": "v4",
    "cpu": "cpu",
}


class CalibrationError(ValueError):
    pass


def chip_slug(device_kind: str) -> str:
    """Canonical file slug for a ``device_kind`` string (runtime
    ``device_kind`` and planner ``chip`` names both normalize here, so
    a table measured on 'TPU v5 lite' serves a target chip 'v5e')."""
    kind = device_kind.lower()
    for key in sorted(_SLUGS, key=len, reverse=True):
        if key in kind:
            return _SLUGS[key]
    return "".join(c if c.isalnum() else "_" for c in kind).strip("_")


def _canon(obj):
    return json.loads(json.dumps(obj, sort_keys=True))


@dataclass
class CalibrationTable:
    """Measured curves for one device kind (module docstring has the
    x-axis conventions). ``collectives`` maps kind -> sorted
    ``[[accounted_bytes, seconds], ...]``; ``matmul`` is sorted
    ``[[flops, flops_per_s], ...]``."""

    device_kind: str
    platform: str
    n_devices: int
    collectives: dict = field(default_factory=dict)
    matmul: list = field(default_factory=list)
    meta: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.collectives = {
            k: sorted([float(b), float(s)] for b, s in pts)
            for k, pts in self.collectives.items()}
        self.matmul = sorted([float(f), float(r)]
                             for f, r in self.matmul)

    def fingerprint(self) -> str:
        body = dataclasses.asdict(self)
        blob = json.dumps(_canon(body), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # -- cost-model lookups -------------------------------------------

    def collective_seconds(self, kind: str, nbytes: float) -> float:
        """Seconds for ``nbytes`` accounted bytes of ``kind``
        (piecewise-linear; latency floor below the smallest measured
        point, tail-bandwidth extrapolation above the largest)."""
        pts = self.collectives.get(kind)
        if not pts:
            raise CalibrationError(
                f"calibration table for '{self.device_kind}' has no "
                f"curve for collective kind '{kind}' "
                f"(has: {sorted(self.collectives)})")
        if nbytes <= pts[0][0]:
            return pts[0][1]
        if nbytes >= pts[-1][0]:
            if len(pts) >= 2:
                (b0, t0), (b1, t1) = pts[-2], pts[-1]
                if t1 > t0 and b1 > b0:
                    return t1 + (nbytes - b1) * (t1 - t0) / (b1 - b0)
            # Degenerate tail (single point / non-monotonic noise):
            # scale by the last point's aggregate rate.
            return pts[-1][1] * nbytes / max(pts[-1][0], 1.0)
        for (b0, t0), (b1, t1) in zip(pts, pts[1:]):
            if b0 <= nbytes <= b1:
                if b1 == b0:
                    return max(t0, t1)
                w = (nbytes - b0) / (b1 - b0)
                return t0 + w * (t1 - t0)
        return pts[-1][1]  # unreachable; defensive

    def achievable_flops_per_s(self, flops: float) -> float:
        """Achieved matmul FLOPs/s at problem size ``flops``
        (piecewise-linear, clamped at both ends — achievable
        throughput saturates, it does not extrapolate)."""
        pts = self.matmul
        if not pts:
            raise CalibrationError(
                f"calibration table for '{self.device_kind}' has no "
                "matmul curve")
        if flops <= pts[0][0]:
            return pts[0][1]
        if flops >= pts[-1][0]:
            return pts[-1][1]
        for (f0, r0), (f1, r1) in zip(pts, pts[1:]):
            if f0 <= flops <= f1:
                if f1 == f0:
                    return max(r0, r1)
                w = (flops - f0) / (f1 - f0)
                return r0 + w * (r1 - r0)
        return pts[-1][1]  # unreachable; defensive

    def fitted_summary(self) -> dict:
        """Human-facing piecewise-fit summary: per-kind latency floor
        and peak bandwidth, peak achieved matmul FLOPs/s. Derived,
        informational — the load-bearing data is the points."""
        out: dict = {"collectives": {}, "matmul": {}}
        for kind, pts in self.collectives.items():
            out["collectives"][kind] = {
                "latency_s": pts[0][1],
                "peak_bytes_per_s": max(
                    (b / t) for b, t in pts if t > 0),
            }
        if self.matmul:
            out["matmul"] = {
                "peak_flops_per_s": max(r for _f, r in self.matmul)}
        return out

    # -- (de)serialization --------------------------------------------

    def to_doc(self) -> dict:
        return {
            "schema": SCHEMA,
            "fingerprint": self.fingerprint(),
            **dataclasses.asdict(self),
            "fitted": self.fitted_summary(),
        }

    @staticmethod
    def from_doc(doc: dict) -> "CalibrationTable":
        if doc.get("schema") != SCHEMA:
            raise CalibrationError(
                f"calibration table schema {doc.get('schema')!r} != "
                f"{SCHEMA} — regenerate with benchmarks/calibrate.py")
        table = CalibrationTable(**{
            k: doc[k] for k in ("device_kind", "platform", "n_devices",
                                "collectives", "matmul", "meta")})
        recorded = doc.get("fingerprint")
        if recorded and recorded != table.fingerprint():
            raise CalibrationError(
                f"calibration table for '{table.device_kind}' "
                f"fingerprint mismatch: file says {recorded}, content "
                f"hashes to {table.fingerprint()} — the file was "
                "hand-edited; re-measure with benchmarks/calibrate.py")
        return table


def table_path(chip: str, calib_dir: str | None = None) -> str:
    return os.path.join(calib_dir or CALIB_DIR,
                        f"{chip_slug(chip)}.json")


def load_table(path: str) -> CalibrationTable:
    with open(path, encoding="utf-8") as f:
        return CalibrationTable.from_doc(json.load(f))


def save_table(table: CalibrationTable,
               path: str | None = None) -> str:
    path = path or table_path(table.device_kind)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(table.to_doc(), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


class CalibrationLookup(NamedTuple):
    """Result of resolving a chip's committed table. ``status`` is
    the STRUCTURED signal consumers branch on (``"measured"`` /
    ``"missing"`` / ``"unusable"``) — the ``note`` is human/
    provenance prose and free to be reworded."""

    table: CalibrationTable | None
    note: str
    status: str


def lookup_for_chip(chip: str, calib_dir: str | None = None
                    ) -> CalibrationLookup:
    """The committed table matching ``chip``, or None with the reason.

    The note is plan-provenance material either way: which file fed
    the cost model, or WHY the planner fell back to nominal
    constants. An unusable committed table (tampered, truncated,
    wrong schema) falls back LOUDLY (``status="unusable"``) rather
    than failing the search: a stale calibration must not brick
    planning, but the plan must say its scores are nominal."""
    path = table_path(chip, calib_dir)
    if not os.path.exists(path):
        return CalibrationLookup(
            None,
            f"no committed calibration table for chip '{chip}' "
            f"({os.path.relpath(path, REPO)}); using nominal "
            "constants",
            "missing")
    try:
        table = load_table(path)
    # KeyError/TypeError: structurally malformed docs (missing keys,
    # wrong point shapes) — every way a committed file can be broken
    # must land in the documented loud-fallback path, never a
    # planner-bricking traceback.
    except (CalibrationError, OSError, ValueError, KeyError,
            TypeError) as e:
        return CalibrationLookup(
            None,
            f"committed calibration table "
            f"{os.path.relpath(path, REPO)} is unusable ({e}); "
            "FALLING BACK to nominal constants — re-measure with "
            "benchmarks/calibrate.py",
            "unusable")
    return CalibrationLookup(
        table,
        f"calibrated from {os.path.relpath(path, REPO)} "
        f"(device_kind '{table.device_kind}', "
        f"fingerprint {table.fingerprint()})",
        "measured")
