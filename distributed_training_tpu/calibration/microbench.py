"""Micro-benchmarks that fill a CalibrationTable.

Measures, on the CURRENT jax backend (all visible devices in one 1-D
mesh):

- each collective kind the planner prices (``table.COLLECTIVE_KINDS``)
  across a ladder of message sizes — jitted ``shard_map`` programs so
  the timed op is the same XLA collective a training step runs, not a
  python-dispatch artifact;
- dense matmul across a ladder of square shapes — the achievable-FLOPs
  curve (spec-sheet peak is what marketing measured; the cost model
  wants what THIS chip reaches on XLA-compiled einsums).

Timing discipline: jit + one untimed warmup execution (compile and
first-touch allocation excluded), then ``iters`` back-to-back
dispatches with a single ``block_until_ready`` drain — the
once-per-measurement sync, not per-step (benchmarks/bench_multichip.py
precedent). Each point records seconds/op at the table's accounted-
bytes convention (see ``table.py``).

jax is imported inside functions only: callers (the calibrate CLI)
must be able to pin platform env first.
"""

from __future__ import annotations

import time

from distributed_training_tpu.calibration.table import (
    COLLECTIVE_KINDS, CalibrationTable)

# Message-size ladder (accounted bytes). Spans latency-dominated to
# bandwidth-dominated on every backend we target; float32 elements.
DEFAULT_SIZES = (1 << 16, 1 << 18, 1 << 20, 1 << 22, 1 << 23)

# Square matmul edge sizes; flops = 2 * n^3.
DEFAULT_MATMUL_SIZES = (256, 512, 1024, 2048)


def _timeit(fn, *args, iters: int) -> float:
    import jax
    out = fn(*args)
    jax.block_until_ready(out)  # warmup: compile + allocation
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _collective_fns(mesh, n: int):
    """kind -> (jitted shard_map fn, input builder(accounted_bytes)).

    Input shapes are chosen so the ACCOUNTED bytes of the timed op
    equal the requested x (table.py conventions)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def sm(f, ins, outs):
        return jax.jit(shard_map(f, mesh=mesh, in_specs=ins,
                                 out_specs=outs, check_rep=False))

    def sharded_rows(nbytes):
        rows = max(n, int(nbytes) // 4 // n * n)
        return jax.device_put(
            jnp.zeros((rows,), jnp.float32),
            NamedSharding(mesh, P("x")))

    def replicated_rows(nbytes):
        rows = max(n, int(nbytes) // 4 // n * n)
        return jax.device_put(jnp.zeros((rows,), jnp.float32),
                              NamedSharding(mesh, P()))

    perm = [(i, (i + 1) % n) for i in range(n)]
    return {
        # x = full gathered tensor bytes: input is the sharded tensor
        # whose gather materializes x bytes on every device.
        "all-gather": (
            sm(lambda v: jax.lax.all_gather(v, "x", tiled=True),
               P("x"), P()),
            sharded_rows),
        # x = full reduced+scattered tensor bytes.
        "reduce-scatter": (
            sm(lambda v: jax.lax.psum_scatter(v, "x", tiled=True),
               P(), P("x")),
            replicated_rows),
        # x = 2 * tensor bytes (ring RS+AG phases): time an all-reduce
        # of a FULL x/2-byte replica on every device (in_specs P() —
        # a sharded operand would reduce only 1/n of the tensor and
        # under-price all-reduce by ~n x).
        "all-reduce": (
            sm(lambda v: jax.lax.psum(v, "x"), P(), P()),
            lambda nbytes: replicated_rows(nbytes / 2.0)),
        # x = bytes each device ships per permute: global tensor of
        # n * x bytes, every device rotates its x-byte shard.
        "ppermute": (
            sm(lambda v: jax.lax.ppermute(v, "x", perm),
               P("x"), P("x")),
            lambda nbytes: sharded_rows(nbytes * n)),
    }


def bench_collectives(sizes=DEFAULT_SIZES, iters: int = 10) -> dict:
    """kind -> [[accounted_bytes, seconds], ...] on all devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError(
            "collective calibration needs >= 2 devices (got "
            f"{len(devs)}); on CPU set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N")
    mesh = Mesh(np.array(devs), ("x",))
    fns = _collective_fns(mesh, len(devs))
    assert set(fns) == set(COLLECTIVE_KINDS)
    out: dict = {}
    for kind in COLLECTIVE_KINDS:
        fn, build = fns[kind]
        pts = []
        for nbytes in sorted(sizes):
            x = build(nbytes)
            pts.append([float(nbytes),
                        _timeit(fn, x, iters=iters)])
        out[kind] = pts
    return out


def bench_matmul(sizes=DEFAULT_MATMUL_SIZES, iters: int = 10) -> list:
    """[[flops, achieved_flops_per_s], ...] for square f32 matmuls —
    the per-device achievable-compute curve, measured with EVERY
    device computing concurrently (one matmul per device via a
    sharded batch). The cost model divides a step's FLOPs across all
    devices running at once; a solo-device measurement would be
    honest on a real slice (each chip owns its compute) but ~n x
    optimistic on the fake-CPU meshes that share one host's cores."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("x",))
    f = jax.jit(shard_map(
        lambda m: jnp.einsum("bij,bjk->bik", m, m),
        mesh=mesh, in_specs=P("x"), out_specs=P("x")))
    pts = []
    for edge in sorted(sizes):
        a = jax.device_put(jnp.ones((n, edge, edge), jnp.float32),
                           NamedSharding(mesh, P("x")))
        secs = _timeit(f, a, iters=iters)
        flops = 2.0 * edge ** 3  # per device, all devices concurrent
        pts.append([flops, flops / secs])
    return pts


def calibrate(sizes=DEFAULT_SIZES, matmul_sizes=DEFAULT_MATMUL_SIZES,
              iters: int = 10, note: str = "") -> CalibrationTable:
    """Run the full micro-benchmark suite and assemble the table for
    this backend's device kind."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return CalibrationTable(
        device_kind=dev.device_kind,
        platform=dev.platform,
        n_devices=len(jax.devices()),
        collectives=bench_collectives(sizes, iters=iters),
        matmul=bench_matmul(matmul_sizes, iters=iters),
        meta={
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "iters": iters,
            "note": note or (
                "measured by benchmarks/calibrate.py; x-axis "
                "conventions in calibration/table.py"),
        })
