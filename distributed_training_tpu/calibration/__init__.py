"""Measured hardware calibration for the planner's cost model.

ROADMAP item 3(a): the planner's roofline used one nominal ICI
bandwidth and a spec-sheet peak for every ``device_kind``. This
package replaces "nominal" with "measured where we have measurements":
``benchmarks/calibrate.py`` micro-benchmarks the collectives the cost
model prices (all-gather, reduce-scatter, all-reduce, ppermute across
message sizes) and matmul shapes on the CURRENT backend, fits
piecewise latency/bandwidth and achievable-FLOPs curves, and commits
them as a fingerprinted ``conf/calibration/<chip>.json``. The planner
(``parallel/planner.py``) consumes the committed table when one
matches the target chip and falls back to per-kind nominal constants
otherwise — with the decision (and the table's fingerprint) recorded
in plan provenance so ``planner --check`` catches drift.

``table``: the stdlib-only artifact layer (schema, fingerprint,
interpolation, chip-slug lookup) — importable by gates and launchers
that must never touch jax. ``microbench``: the jax measurement layer.
"""

from distributed_training_tpu.calibration.table import (  # noqa: F401
    COLLECTIVE_KINDS, CalibrationError, CalibrationLookup,
    CalibrationTable, chip_slug, load_table, lookup_for_chip,
    save_table, table_path)
