"""Playground: distributed training re-derived from collective primitives.

The pedagogical layer — parity with the reference's
``src/playground/ddp_script.py`` ("DDP from ground up", README.md:24-26):
where the production trainer lets XLA *infer* collectives from sharding
layouts, the playground calls them *explicitly* so you can see exactly
what data parallelism is made of.
"""

from distributed_training_tpu.playground.ddp_from_primitives import (  # noqa: F401,E501
    train_ddp,
)
