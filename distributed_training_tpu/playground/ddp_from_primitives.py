"""DDP from collective primitives — the TPU re-derivation.

Line-for-line *conceptual* parity with the reference's pedagogical script
(src/playground/ddp_script.py), whose recipe is (SURVEY.md §3.2):

1. identical seed on every rank                 (ddp_script.py:108)
2. broadcast params from rank 0                 (:120-121)
3. shard the dataset by rank                    (:124-132)
4. forward/backward locally, then per-parameter
   ``all_reduce(SUM) / world_size``             (:149-154)
5. identical optimizer step on every rank       (:166)
6. optional per-rank grad/weight-norm logging   (:155-164, behind a
   debug flag here — always-on was reference bug B8)

The TPU translation: "ranks" are devices on a 1-D ``dp`` mesh inside one
process; per-rank code is the function passed to ``shard_map``, and the
collectives are explicit ``jax.lax`` calls — ``pmean`` for the gradient
all-reduce (psum/world_size, exactly Q10's convention) and ``ppermute``
broadcast for the initial param sync. Everything the production trainer
gets implicitly from sharding layouts is spelled out here by hand.

Run:  python -m distributed_training_tpu.playground.ddp_from_primitives \
          --world-size 4 --epochs 3 [--log-norms]
"""

from __future__ import annotations

import argparse
import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


# -- model: SimpleModel = Linear(10, 1) (parity: ddp_script.py:16-23) ----


def init_params(rng: jax.Array, in_dim: int = 10) -> dict:
    bound = 1.0 / np.sqrt(in_dim)
    wk, bk = jax.random.split(rng)
    return {
        "w": jax.random.uniform(wk, (in_dim, 1), jnp.float32,
                                -bound, bound),
        "b": jax.random.uniform(bk, (1,), jnp.float32, -bound, bound),
    }


def forward(params: dict, x: jax.Array) -> jax.Array:
    return x @ params["w"] + params["b"]


def mse_loss(params: dict, x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.mean((forward(params, x) - y) ** 2)  # ddp_script.py:135


# -- dataset: DummyDataset randn pairs (parity: ddp_script.py:26-36) -----


def make_dataset(size: int = 1000, in_dim: int = 10, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((size, in_dim)).astype(np.float32)
    y = rng.standard_normal((size, 1)).astype(np.float32)
    return x, y


# -- the per-rank program ------------------------------------------------


def _rank_step(params, x_local, y_local, lr, *, log_norms):
    """What ONE rank does for one batch. Runs under shard_map: shapes
    here are per-device shards and collectives are explicit."""
    # (4) local forward/backward…
    loss, grads = jax.value_and_grad(mse_loss)(params, x_local, y_local)

    # …then the gradient all-reduce. pmean == psum / axis_size: the
    # allreduce-SUM-then-divide convention of ddp_script.py:150-154 (Q10).
    grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
    # Each rank also averages its loss for reporting (not required for
    # correctness — gradients are already synced).
    mean_loss = jax.lax.pmean(loss, "dp")

    # (5) identical SGD step on every rank — replicas stay in lockstep.
    params = jax.tree.map(lambda p, g: p - lr * g, params, grads)

    # Per-rank values get a leading length-1 axis so shard_map can
    # concatenate them over 'dp' (out_specs P('dp')) — without that they
    # would collapse to one undefined replica's value at the boundary.
    metrics = {"loss": mean_loss, "local_loss": loss[None]}
    if log_norms:
        # (6) per-param grad/weight norms, per rank (ddp_script.py:155-164)
        metrics["grad_norms"] = jax.tree.map(
            lambda g: jnp.linalg.norm(g)[None], grads)
        metrics["param_norms"] = jax.tree.map(
            lambda p: jnp.linalg.norm(p)[None], params)
    return params, metrics


def _broadcast_from_rank0(params, mesh: Mesh):
    """(2) param broadcast. Seeding already makes replicas identical
    (ddp_script.py:108); the broadcast is belt-and-braces exactly like
    the reference (:118-121). Expressed as: zero out every rank's params
    except rank 0, then psum — a broadcast built from an all-reduce."""

    def bcast(p):
        rank = jax.lax.axis_index("dp")
        keep = jnp.where(rank == 0, 1.0, 0.0)
        return jax.lax.psum(p * keep, "dp")

    fn = shard_map(
        lambda t: jax.tree.map(bcast, t),
        mesh=mesh, in_specs=P(), out_specs=P(), check_rep=False)
    return fn(params)


def train_ddp(world_size: int | None = None, epochs: int = 3,
              batch_size: int = 32, lr: float = 0.01,
              dataset_size: int = 1000, seed: int = 42,
              log_norms: bool = False, log_dir: str | None = None,
              devices=None) -> dict:
    """Run the pedagogical DDP loop; returns final params + history."""
    devices = devices or jax.devices()
    world_size = world_size or len(devices)
    if world_size > len(devices):
        raise ValueError(
            f"world_size {world_size} > available devices "
            f"{len(devices)}")
    mesh = Mesh(np.asarray(devices[:world_size]), ("dp",))
    logger.info("playground DDP: world_size=%d on %s", world_size,
                devices[0].platform)

    if log_dir:  # per-rank log files (ddp_script.py:70-78)
        os.makedirs(log_dir, exist_ok=True)

    # (1) identical seed everywhere → identical init (ddp_script.py:108)
    params = init_params(jax.random.PRNGKey(seed))
    # (2) broadcast from rank 0
    params = _broadcast_from_rank0(params, mesh)

    x, y = make_dataset(dataset_size, seed=seed)
    # (3) shard data by rank — same strided DistributedSampler arithmetic
    # as production (data/sampler.py)
    from distributed_training_tpu.data.sampler import (
        DistributedShardSampler,
    )
    sampler = DistributedShardSampler(dataset_size, world_size,
                                      shuffle=True, seed=seed)

    batch_sharding = NamedSharding(mesh, P("dp"))
    metric_specs = {"loss": P(), "local_loss": P("dp")}
    if log_norms:
        ptree = jax.tree.map(lambda _: P("dp"), params)
        metric_specs["grad_norms"] = ptree
        metric_specs["param_norms"] = ptree
    step = shard_map(
        functools.partial(_rank_step, log_norms=log_norms),
        mesh=mesh,
        in_specs=(P(), P("dp"), P("dp"), P()),
        out_specs=(P(), metric_specs),
        check_rep=False,
    )
    # Donate the params buffer: the caller rebinds ``params`` to the
    # step's output every iteration, so the old copy is dead — same
    # contract as the production trainer's donate_argnums=(0,).
    step = jax.jit(step, static_argnames=(), donate_argnums=(0,))

    steps_per_epoch = sampler.num_samples // batch_size
    history: list[dict] = []
    for epoch in range(epochs):
        sampler.set_epoch(epoch)  # reshuffle (ddp_script.py:140)
        shard_idx = np.stack([sampler.shard_indices(r)
                              for r in range(world_size)])
        epoch_losses = []
        for s in range(steps_per_epoch):
            rows = shard_idx[:, s * batch_size:(s + 1) * batch_size]
            xb = jax.device_put(x[rows.reshape(-1)], batch_sharding)
            yb = jax.device_put(y[rows.reshape(-1)], batch_sharding)
            lr_arr = jnp.float32(lr)
            params, metrics = step(params, xb, yb, lr_arr)
            epoch_losses.append(float(metrics["loss"]))
            if log_norms and log_dir:
                _write_rank_logs(log_dir, epoch, s, metrics, world_size)
        entry = {"epoch": epoch,
                 "mean_loss": float(np.mean(epoch_losses))}
        history.append(entry)
        logger.info("epoch %d | mean_loss %.6f", epoch,
                    entry["mean_loss"])

    return {"params": params, "history": history, "mesh": mesh}


def _write_rank_logs(log_dir, epoch, step, metrics, world_size):
    """Per-rank log files like logs/ddp_rank_<r>.log (ddp_script.py:74).
    ``metrics['local_loss']`` etc. carry one entry per rank."""
    local = np.asarray(metrics["local_loss"])
    gnorms = {k: np.asarray(v) for k, v in
              _flatten(metrics.get("grad_norms", {})).items()}
    pnorms = {k: np.asarray(v) for k, v in
              _flatten(metrics.get("param_norms", {})).items()}
    for r in range(world_size):
        path = os.path.join(log_dir, f"ddp_rank_{r}.log")
        norm_txt = " ".join(f"|g[{k}]|={v[r]:.4f}"
                            for k, v in gnorms.items())
        wnorm_txt = " ".join(f"|w[{k}]|={v[r]:.4f}"
                             for k, v in pnorms.items())
        with open(path, "a") as f:
            f.write(f"epoch={epoch} step={step} "
                    f"local_loss={local[r]:.6f} {norm_txt} "
                    f"{wnorm_txt}\n")


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix.rstrip(".")] = tree
    return out


def main(argv=None) -> int:
    # argparse CLI, parity: ddp_script.py:186-241
    p = argparse.ArgumentParser(
        description="DDP from collective primitives (pedagogical)")
    p.add_argument("--world-size", type=int, default=None,
                   help="ranks (devices); default: all devices")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--dataset-size", type=int, default=1000)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--log-norms", action="store_true",
                   help="per-rank grad/weight norm logging (ref B8: "
                        "off by default, it is instrumentation)")
    p.add_argument("--log-dir", default="logs")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    from distributed_training_tpu.runtime import apply_env_platforms
    apply_env_platforms()
    result = train_ddp(
        world_size=args.world_size, epochs=args.epochs,
        batch_size=args.batch_size, lr=args.lr,
        dataset_size=args.dataset_size, seed=args.seed,
        log_norms=args.log_norms, log_dir=args.log_dir)
    print(f"final mean_loss: {result['history'][-1]['mean_loss']:.6f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
