"""Generation CLI: sample from a trained checkpoint.

No counterpart exists in the reference (its models are Linear
regressors, src/distributed_trainer.py:199); this closes the loop the
transformer families open — train with the trainer CLI, then:

    # Byte-level models (vocab 256): the prompt is literal UTF-8 —
    # no tokenizer download, nothing to install.
    python -m distributed_training_tpu.generate \
        --run-dir outputs/default --prompt "def main(" \
        --max-new-tokens 128 --temperature 0.8 --top-k 40

    # Token models: ids in, ids out.
    python -m distributed_training_tpu.generate \
        --run-dir outputs/gpt2 --prompt-ids 50256,318 -n 32

The model is rebuilt from the run's own ``resolved_config.yaml`` (the
exact architecture that trained) and params come from the newest step
under the run's checkpoint dir — or pass ``--artifact`` for a
consolidated single-file export (checkpoint/export.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_run_config(run_dir: str):
    from distributed_training_tpu.config import config_from_dict

    import yaml

    path = os.path.join(run_dir, "resolved_config.yaml")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found — point --run-dir at a training run "
            "directory (<run.output_dir>/<run.experiment_name>)")
    with open(path) as f:
        return config_from_dict(yaml.safe_load(f))


def _build_model_from_cfg(cfg):
    """Rebuild the exact trained architecture from a run's resolved
    config (shared by the generate and eval CLIs — the dtype-pop rule
    must not drift between them)."""
    from distributed_training_tpu.models import build_model

    model_kwargs = dict(cfg.model.kwargs)
    model_dtype = model_kwargs.pop("dtype", cfg.train.dtype)
    return build_model(cfg.model.name, loss=cfg.train.loss,
                       dtype=model_dtype, **model_kwargs)


def _restore_params(run_dir: str, snapshot_path: str,
                    step: int | None):
    """Newest (or given) step's params onto the local default device
    (checkpoint/export.py::restore_step_local). ``snapshot_path`` was
    anchored absolute on the TRAINING machine; when a copied run dir
    no longer has it, fall back to the checkpoint dir inside
    ``run_dir`` itself (the host-side-sampling use case)."""
    from distributed_training_tpu.checkpoint.export import (
        restore_step_local,
    )

    ckpt_dir = snapshot_path
    if not os.path.isdir(ckpt_dir):
        local = os.path.join(run_dir,
                             os.path.basename(snapshot_path.rstrip(
                                 os.sep)) or "checkpoints")
        if not os.path.isdir(local):
            raise FileNotFoundError(
                f"no checkpoint dir at {snapshot_path} (from the "
                f"run's resolved config) nor at {local}")
        ckpt_dir = local
    state, step = restore_step_local(ckpt_dir, step)
    return state["params"], step


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtt-generate",
        description="Sample from a trained checkpoint")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--run-dir",
                     help="training run dir (holds resolved_config."
                          "yaml + checkpoints)")
    src.add_argument("--artifact",
                     help="consolidated single-file export "
                          "(checkpoint/export.py); artifacts written "
                          "by this framework carry the architecture "
                          "in their meta — --model-name/--model-kwargs "
                          "override or fill in for foreign artifacts")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest)")
    prompt = p.add_mutually_exclusive_group(required=True)
    prompt.add_argument("--prompt",
                        help="UTF-8 text prompt (byte-vocab models)")
    prompt.add_argument("--prompt-ids",
                        help="comma-separated token ids")
    p.add_argument("-n", "--max-new-tokens", type=int, default=64)
    p.add_argument("--decode", choices=("paged", "fused"),
                   default="paged",
                   help="greedy decode path: 'paged' (default) runs "
                        "the serving KV-cache decode step "
                        "(serving/engine.py — token-for-token equal "
                        "to the full-context path, pinned by test); "
                        "'fused' keeps the model's dense-cache "
                        "generate loop. Sampling (temperature > 0) "
                        "always uses 'fused' for rng-stream "
                        "stability.")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model-name", default=None)
    p.add_argument("--model-kwargs", default="{}",
                   help="JSON dict (with --artifact)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_argparser().parse_args(argv)

    import jax

    # Site customizations may pin the platform at interpreter start,
    # overriding the env var — re-apply it so JAX_PLATFORMS=cpu really
    # does keep host-side sampling off a (possibly sick) accelerator
    # (same contract as checkpoint/export.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import numpy as np

    from distributed_training_tpu.models import build_model

    if args.run_dir:
        cfg = _load_run_config(args.run_dir)
        model = _build_model_from_cfg(cfg)
        params, step = _restore_params(args.run_dir,
                                       cfg.train.snapshot_path,
                                       args.step)
    else:
        if args.step is not None:
            raise ValueError(
                "--step selects a step inside a run dir; a "
                "consolidated artifact holds exactly one step "
                "(re-export with checkpoint/export.py --step N)")
        from distributed_training_tpu.checkpoint.consolidate import (
            load_consolidated,
        )
        state, meta = load_consolidated(args.artifact)
        name = args.model_name or meta.get("model_name")
        if not name:
            raise ValueError(
                "--artifact carries no architecture meta (foreign or "
                "pre-r4 export) — pass --model-name and "
                "--model-kwargs")
        # Meta fills in, explicit CLI flags win per-key ("override or
        # fill in") — regardless of which of the two flags was given.
        kwargs = dict(meta.get("model_kwargs") or {})
        kwargs.setdefault("dtype", meta.get("model_dtype", "float32"))
        kwargs.setdefault("loss", meta.get("loss", "auto"))
        kwargs.update(json.loads(args.model_kwargs))
        model = build_model(name, **kwargs)
        params = jax.tree.map(jnp.asarray, state["params"])
        step = meta.get("step", -1)

    if not hasattr(model, "generate"):
        raise ValueError(
            f"model family '{type(model).__name__}' has no "
            "autoregressive decode path — generation needs a "
            "transformer-family checkpoint")
    vocab = model.cfg.vocab_size
    if args.prompt is not None:
        if vocab != 256:
            raise ValueError(
                f"--prompt is UTF-8 bytes, which needs a byte-vocab "
                f"(256) model; this one has vocab {vocab} — pass "
                "--prompt-ids instead")
        ids = np.frombuffer(args.prompt.encode("utf-8"),
                            dtype=np.uint8).astype(np.int32)
    else:
        ids = np.asarray([int(t) for t in
                          args.prompt_ids.split(",")], np.int32)
        if ids.size and (ids.min() < 0 or ids.max() >= vocab):
            raise ValueError(
                f"prompt ids must be in [0, {vocab}), got "
                f"[{ids.min()}, {ids.max()}]")
    if ids.size == 0:
        raise ValueError("empty prompt")

    paged = (args.decode == "paged" and args.temperature <= 0
             and hasattr(model, "prefill")
             and getattr(model.cfg, "moe_num_experts", 0) == 0)
    if paged:
        # The serving decode path: a one-slot continuous-batching
        # engine over the paged KV cache — each token reads only the
        # cache, never the full context (the serving subsystem's
        # step, reused; parity with the full-context argmax is
        # pinned in tests/test_generate_cli.py).
        from distributed_training_tpu.serving.engine import (
            Engine, EngineConfig)
        page = 16
        total = int(ids.size) + args.max_new_tokens
        # Pool capacity: pages for the whole request, capped at the
        # model's window FLOORED to a page multiple (the cache
        # requires it). A request that only fits the un-floored
        # window takes the fused path below instead of failing.
        model_cap = model.cfg.max_seq_len // page * page
        max_len = min(-(-total // page) * page, model_cap)
        if total > max_len:
            paged = False
        else:
            eng = Engine(model, params, EngineConfig(
                max_batch=1, page_size=page,
                num_pages=-(-max_len // page) + 1,
                max_seq_len=max_len,
                prefill_chunk=min(64, max_len)))
            out_ids = np.asarray(
                eng.generate(ids, args.max_new_tokens), np.int32)
    if not paged:
        prompt = jnp.asarray(ids)[None, :]
        rng = jax.random.PRNGKey(args.seed)
        out = model.generate(params, prompt,
                             max_new_tokens=args.max_new_tokens,
                             temperature=args.temperature,
                             top_k=args.top_k, rng=rng)
        out_ids = np.asarray(out[0])
    print(f"# step={step} prompt_tokens={ids.size} "
          f"sampled={out_ids.size}", file=sys.stderr)
    if vocab == 256:
        print(bytes(out_ids.astype(np.uint8)).decode(
            "utf-8", errors="replace"))
    else:
        print(",".join(str(int(t)) for t in out_ids))
    return 0


if __name__ == "__main__":
    sys.exit(main())
