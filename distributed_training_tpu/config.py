"""Config layer: YAML composition + dotted CLI overrides → typed dataclasses.

TPU-native replacement for the reference's Hydra setup
(reference: conf/config.yaml:1-14, src/distributed_trainer.py:29-39,243-258).
We keep the same user-facing model — a composition root YAML with
``defaults`` groups (``model``, ``train``, plus ``mesh``) and
``key.path=value`` command-line overrides — but implement it as a small,
dependency-free loader so the framework controls run-dir/chdir behavior
explicitly (the reference's Hydra chdir breaks resume; SURVEY.md §8 B2).

Grammar:
- ``group=name``      swap a defaults-group file (e.g. ``model=gpt2_125m``)
- ``a.b.c=value``     set a leaf (value parsed with yaml.safe_load)
- ``+a.b.c=value``    add a new leaf that need not already exist
"""

from __future__ import annotations

import copy
import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any

import yaml


class ConfigError(ValueError):
    """Raised for malformed config files or overrides."""


# ---------------------------------------------------------------------------
# Typed config schema
# ---------------------------------------------------------------------------


@dataclass
class TrainConfig:
    """Training knobs; field-for-field superset of the reference's
    ``TrainingConfig`` (reference: src/distributed_trainer.py:29-39,
    conf/train/default.yaml)."""

    batch_size: int = 32          # per-process batch size, as in the reference
    # Elastic runs: a WORLD-SIZE-INVARIANT global batch. When > 0 the
    # CLI derives the per-shard batch_size as global_batch_size /
    # data_shard_count at startup, so a run that shrinks from 4 hosts
    # to 3 keeps the same optimization trajectory (pick a value
    # divisible by every world size the run can shrink to, e.g. 12
    # for 4-or-3). 0 keeps the legacy per-shard batch_size semantics.
    global_batch_size: int = 0
    total_epochs: int = 10
    save_every: int = 2           # epochs between checkpoints
    snapshot_path: str = "checkpoints"  # absolute-anchored at load (fixes B2)
    # Also export a gathered single-file artifact at every save point
    # (the reference FSDP FULL_STATE_DICT analogue; consolidate.py).
    gather_on_save: bool = False
    # Keep optimizer moments resident in pinned host memory BETWEEN
    # steps (streamed to device around each compiled step) — the
    # analogue of the reference FSDP's CPU offload (fsdp_strategy.py:
    # 23-25). Note: step-peak HBM is unchanged (the moments visit the
    # device for the update); this frees between-step residency, at
    # the cost of two opt-state transfers per step.
    offload_opt_state: bool = False
    # FSDP compute contract: constrain weights replicated at their
    # cast-to-compute sites so XLA all-gathers each weight for its
    # matmuls (layer-by-layer inside the scan, bf16, transient)
    # instead of all-reducing partial-product ACTIVATIONS — measured
    # via benchmarks/audit_collectives.py, the partitioner otherwise
    # chooses activation-shaped collectives that dwarf FSDP's param
    # traffic. Applies only when parallel_strategy == "fsdp" and the
    # model supports the binding.
    fsdp_gather_for_compute: bool = True
    # Durable metrics stream: coordinator appends every recorded entry
    # (loss, samples/sec/chip, mfu, val_loss) as one JSON line. Empty →
    # disabled; the CLI defaults it to <run_dir>/metrics.jsonl.
    metrics_jsonl: str = ""
    # Structured telemetry stream (spans, goodput windows, hbm samples
    # — see docs/observability.md). Empty → disabled; the CLI defaults
    # it to <run_dir>/events.jsonl on the coordinator.
    events_jsonl: str = ""
    # Hang watchdog: a step armed longer than this dumps a postmortem
    # bundle (all-thread stacks, per-device memory_stats, last events)
    # to <run_dir>/postmortem/. 0 disables. Set it to a generous
    # multiple of the expected step time — compile is excluded (the
    # first step arms with a 10x allowance).
    watchdog_timeout_s: float = 0.0
    # After the postmortem: hard-exit (rc 42)? Default off — an
    # attended run may recover; unattended launchers want the abort so
    # a hung process doesn't hold the accelerator forever.
    watchdog_abort: bool = False
    # Steps between hbm telemetry samples (device.memory_stats() into
    # the event stream). 0 disables.
    hbm_sample_every: int = 0
    # Cross-host straggler detector (telemetry/straggler.py): every N
    # optimizer steps all hosts exchange their window step/data_wait
    # means over a tiny host-level all-gather and flag hosts
    # persistently above threshold x the cross-host median. Off the
    # critical path (one small f32 vector per window); auto-disabled
    # when process_count == 1. 0 disables the exchange entirely.
    straggler_every: int = 100
    straggler_threshold: float = 1.5
    # Consecutive flagged windows before a verdict (one slow window is
    # noise — host GC, a checkpoint drain; a persistent 2x is a
    # failing host).
    straggler_persist: int = 2
    # Consecutive flagged windows before the detector requests a
    # COORDINATED EVICTION of the worst host: every host (same
    # all-gathered table, same step) breaks its loop, saves, and exits
    # with a host_lost sentinel the elastic supervisor consumes —
    # never an in-band kill. 0 disables (verdicts stay advisory).
    # Meaningful under launch.local --supervise --elastic.
    straggler_evict_after: int = 0
    # One-shot static audit of the compiled step's collective traffic
    # (telemetry/collectives.py): after the first step the coordinator
    # lowers+compiles the same program device-less and emits a
    # `collectives` event (op counts + bytes/step per mesh axis) so
    # the summarizer can print a comms roofline next to MFU. Costs one
    # extra (cache-warm trace) compile on the coordinator; only runs
    # when an event sink is installed.
    collectives_audit: bool = True
    dataset_size: int = 2048
    learning_rate: float = 1e-3
    device: str = "auto"          # "auto" | "tpu" | "cpu"
    # "ddp" | "fsdp" (reference parity) + framework extensions:
    # "zero1" (DDP compute, moments sharded over data axes),
    # "hybrid" (FSDP in-slice, replicate across dp), "tp".
    parallel_strategy: str = "ddp"
    # Resolved auto-parallelism plan (parallel/planner.py): a
    # committed plan name (conf/plans/<name>.json) or a path. When
    # set, the trainer compiles against the plan's sharding-map-by-
    # name (PlannedStrategy) instead of parallel_strategy's ad-hoc
    # specs, and the CLI derives cfg.mesh from the plan (dp as the
    # elastic wildcard). Empty → legacy per-strategy specs.
    sharding_plan: str = ""
    # Comms/compute overlap scheduling (parallel/overlap.py): when a
    # plan is pinned, derive the XLA latency-hiding-scheduler (and
    # collective-combiner) flags from it and append them to XLA_FLAGS
    # before the backend initializes — the SimpleFSDP discipline of
    # hiding FSDP's all-gather/reduce-scatter under compute via the
    # COMPILER's schedule. The static overlap ratchet
    # (analysis/OVERLAP_baseline.json) scores the same flags; flags
    # already present in XLA_FLAGS are never overridden. False
    # reproduces the unscheduled (pre-r07) behavior.
    xla_overlap_flags: bool = True
    seed: int = 42
    optimizer: str = "sgd"        # "sgd" | "adamw" | "adafactor"
    weight_decay: float = 0.0
    # AdamW decay scope: "all" = every param (torch.optim.AdamW's
    # default, the parity baseline); "matrices" = only >=2-D params
    # (the transformer convention — biases/LayerNorm excluded).
    decay_mask: str = "all"
    b1: float = 0.9
    b2: float = 0.95
    grad_clip_norm: float = 0.0   # 0 disables
    warmup_steps: int = 0
    lr_schedule: str = "constant"  # "constant" | "cosine"
    total_steps: int = 0          # 0 → derived from epochs * steps/epoch
    log_every: int = 10           # steps between metric lines
    dtype: str = "float32"        # compute dtype: "float32" | "bfloat16"
    param_dtype: str = "float32"
    remat: bool = False           # gradient checkpointing for big models
    # Microbatches accumulated per optimizer step (1 = off). The global
    # batch must split evenly: batch_size % grad_accum_steps == 0 per
    # shard. Peak activation memory scales with batch/grad_accum_steps.
    grad_accum_steps: int = 1
    loss: str = "auto"            # "auto" | "mse" | "xent" | "prob_xent"
    dataset: str = "synthetic"    # data source name
    dataset_kwargs: dict[str, Any] = field(default_factory=dict)
    # Multi-source exactly-once streaming pipeline (data/stream.py).
    # Non-empty switches the train loader to StreamingDataLoader:
    # ``{name: {dataset: <registry name>, weight: W, **kwargs}}``.
    # The pipeline's whole position (per-source cursors, mixture,
    # packing carry) is serialized into every checkpoint, so restarts
    # and elastic resizes resume mid-epoch exactly-once — pair with
    # train.global_batch_size so the stream is world-size-invariant.
    # Mutually exclusive with eval_fraction (no held-out split yet).
    data_sources: dict[str, Any] = field(default_factory=dict)
    # Sequence packing (streaming pipeline only): concatenate
    # documents across boundaries into fixed blocks of pack_seq_len
    # tokens (+1 for the next-token shift) — no padding, so
    # tokens/step rises to the full block on ragged corpora. 0 = one
    # row per document (sources must then share a row length).
    pack_seq_len: int = 0
    shuffle: bool = True
    drop_last: bool = False
    max_steps_per_epoch: int = 0  # 0 → whole shard (test/bench aid)
    nan_guard: bool = False       # skip+log non-finite update steps
    # Held-out evaluation: eval_fraction of the dataset is split off
    # (deterministically, seed-keyed) and scored every eval_every
    # epochs with dropout off and no state update. 0 disables either.
    eval_fraction: float = 0.0
    eval_every: int = 1           # epochs between evals (if enabled)
    min_shard_elems: int = 4096   # FSDP: replicate arrays smaller than this
    divergence_check_every: int = 0  # steps; 0 disables replica-drift check
    # Steps between cross-host stop-flag polls (multi-host only). Stop
    # latency on SIGTERM is stop_poll_every * step_time — keep that
    # below the preemption grace window (~30s on GCE); use 1 for steps
    # slower than a few seconds.
    stop_poll_every: int = 8
    profile_dir: str = ""         # non-empty → jax.profiler traces here
    # In-run profiler capture + step-time attribution (telemetry/
    # attribution.py): comma-separated global steps, e.g. "20" or
    # "20,500". At each step the COORDINATOR captures a jax.profiler
    # trace of profile_steps steps into <run_dir>/profiles/ and
    # immediately emits an `attribution` event (compute / collective /
    # host+data fractions + overlap %). One-shot across supervisor
    # restarts. An already-running job is profiled on demand by
    # dropping a file named `profile_now` in the run dir. Empty and no
    # trigger file → off. Mutually exclusive in spirit with
    # profile_dir (a whole-run trace); if both are live the capture
    # declines to start.
    profile_at: str = ""
    profile_steps: int = 2
    # Live metrics endpoint (telemetry/metrics_server.py): when > 0
    # the coordinator serves Prometheus text exposition on this port —
    # GET /metrics (step time, tokens/s, MFU, goodput, data_wait,
    # straggler verdicts, overlap %, world size/incarnation) and GET
    # /healthz (503 once the step loop has stalled past the watchdog
    # threshold). Fed from the same Telemetry sink as events.jsonl —
    # one metrics source of truth. 0 disables.
    metrics_port: int = 0
    # Online anomaly detection + incident flight recorder (telemetry/
    # anomaly.py, telemetry/incident.py). The detector is a pure
    # host-side observer of the event stream (zero new device syncs):
    # rolling median/MAD baselines over step_time / data_wait /
    # throughput / loss / serving signals, `anomaly` events with
    # evidence, a sustained step-time regression arming one in-run
    # profile capture (drops `profile_now`, one-shot across restarts),
    # and incident bundles under <run_dir>/incidents/ on anomaly /
    # watchdog abort / preemption. Coordinator-only. Offline triage:
    # `python -m distributed_training_tpu.telemetry <run_dir> --doctor`.
    anomaly_detect: bool = True
    anomaly_window: int = 64      # rolling baseline window (samples)
    anomaly_min_samples: int = 16  # baseline warmup before verdicts
    anomaly_threshold: float = 8.0  # MADs from median to flag
    anomaly_sustain: int = 5      # consecutive slow steps -> profile
    anomaly_autoprofile: bool = True  # arm profile_now on sustained
    incident_cooldown_s: float = 60.0  # min gap between bundles/kind
    # Deterministic fault injection (resilience/faults.py): e.g.
    # "crash@40,sigterm@80,corrupt_ckpt@120,data_stall@60:500ms".
    # Every trigger is a pure function of the global step (multi-host
    # safe); faults are one-shot across restarts unless marked
    # ":always". Empty disables. Grammar: docs/robustness.md.
    fault_plan: str = ""
    # Transient batch-assembly/IO errors are retried this many times
    # (short exponential backoff, `data_retry` telemetry event) before
    # the step loop is allowed to die. 0 fails on the first blip.
    data_retries: int = 2


@dataclass
class MeshConfig:
    """Logical mesh shape. ``-1`` on exactly one axis means "fill with the
    remaining devices". Axes: dp (pure data parallel, outermost / DCN),
    fsdp (param sharding, ICI), tp (tensor/model), sp (sequence/context),
    ep (expert; folded over fsdp×dp when used), pp (pipeline stages)."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1


@dataclass
class ModelConfig:
    """Model selection + hyperparameters. ``name`` picks the family from the
    registry (models/registry.py); remaining fields are family-specific and
    carried as an open dict so YAML stays the source of truth."""

    name: str = "mlp"
    kwargs: dict[str, Any] = field(default_factory=dict)


@dataclass
class RunConfig:
    """Run environment: output dir, logging."""

    output_dir: str = "outputs"
    log_level: str = "INFO"
    log_file: str = "training.log"
    experiment_name: str = "default"


@dataclass
class Config:
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    run: RunConfig = field(default_factory=RunConfig)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# YAML composition
# ---------------------------------------------------------------------------


def _load_yaml(path: str) -> dict[str, Any]:
    if not os.path.exists(path):
        raise ConfigError(f"config file not found: {path}")
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ConfigError(f"top level of {path} must be a mapping")
    return data


def _deep_merge(base: dict[str, Any], over: dict[str, Any]) -> dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _set_path(tree: dict[str, Any], dotted: str, value: Any,
              allow_new: bool) -> None:
    keys = dotted.split(".")
    node = tree
    for k in keys[:-1]:
        if k in node and not isinstance(node[k], dict):
            raise ConfigError(
                f"override path '{dotted}': '{k}' is a value, not a group")
        if k not in node:
            if not allow_new:
                raise ConfigError(
                    f"override path '{dotted}': unknown key '{k}' "
                    f"(use +{dotted}=... to add new keys)")
            node[k] = {}
        node = node[k]
    leaf = keys[-1]
    if not allow_new and leaf not in node:
        raise ConfigError(
            f"override path '{dotted}': unknown key '{leaf}' "
            f"(use +{dotted}=... to add new keys)")
    node[leaf] = value


def compose(config_dir: str, config_name: str = "config",
            overrides: list[str] | None = None,
            base_tree: dict[str, Any] | None = None) -> dict[str, Any]:
    """Compose the raw config dict: base defaults + root YAML + defaults
    groups + overrides.

    Mirrors the reference's Hydra composition of conf/config.yaml's
    ``defaults: [model: default, train: default]`` (conf/config.yaml:1-4)
    without the chdir side effects. ``base_tree`` (the typed schema's
    defaults) is merged underneath so every schema field is a valid
    override target even when the YAML files don't spell it out.
    """
    overrides = list(overrides or [])
    root = _load_yaml(os.path.join(config_dir, f"{config_name}.yaml"))
    defaults = root.pop("defaults", [])

    # group=name overrides replace default group selections before loading
    group_over: dict[str, str] = {}
    leaf_over: list[tuple[str, str, bool]] = []
    for ov in overrides:
        if "=" not in ov:
            raise ConfigError(f"override '{ov}' must be key=value")
        key, val = ov.split("=", 1)
        allow_new = key.startswith("+")
        key = key.lstrip("+")
        if "." not in key and os.path.isdir(os.path.join(config_dir, key)):
            group_over[key] = val
        else:
            leaf_over.append((key, val, allow_new))

    selections: list[tuple[str, str]] = []
    for entry in defaults:
        if isinstance(entry, str):  # e.g. "_self_"
            continue
        if not isinstance(entry, dict) or len(entry) != 1:
            raise ConfigError(f"bad defaults entry: {entry!r}")
        (group, name), = entry.items()
        selections.append((group, group_over.pop(group, name)))
    selections.extend(group_over.items())

    tree: dict[str, Any] = copy.deepcopy(base_tree) if base_tree else {}
    for group, name in selections:
        group_file = os.path.join(config_dir, group, f"{name}.yaml")
        tree = _deep_merge(tree, {group: _load_yaml(group_file)})

    tree = _deep_merge(tree, root)

    for key, val, allow_new in leaf_over:
        _set_path(tree, key, yaml.safe_load(val),
                  allow_new or _is_open_path(key))
    return tree


def _is_open_path(dotted: str) -> bool:
    """Open-schema override targets need no ``+``: the ``model`` group
    (hyperparameters are family-specific, carried via ModelConfig.kwargs),
    any ``*_kwargs`` mapping (e.g. train.dataset_kwargs), and the
    ``train.data_sources`` mixture tree (source names and their
    dataset kwargs are user-defined)."""
    parts = dotted.split(".")
    if parts[0] == "model" and len(parts) > 1:
        return True
    return any(p.endswith("_kwargs") or p == "data_sources"
               for p in parts[:-1])


# ---------------------------------------------------------------------------
# dict → dataclass
# ---------------------------------------------------------------------------


def _coerce_scalar(ftype: type, v: Any, path: str) -> Any:
    """Coerce YAML scalars into the schema's type. Load-bearing for
    floats: PyYAML's float regex requires a dot, so Hydra-style
    ``train.learning_rate=3e-3`` arrives as the STRING '3e-3' and
    would flow into the optimizer uncoerced."""
    if isinstance(v, bool) or not isinstance(v, (str, int, float)):
        return v
    try:
        if ftype is float and not isinstance(v, float):
            return float(v)
        if ftype is int and isinstance(v, str):
            return int(v)
        if ftype is int and isinstance(v, float):
            if v != int(v):
                raise ValueError(v)  # 2.5 into an int field is junk
            return int(v)
        if ftype is bool and isinstance(v, str):
            lv = v.lower()
            if lv in ("true", "1", "yes"):
                return True
            if lv in ("false", "0", "no"):
                return False
            raise ValueError(v)
    except ValueError as e:
        raise ConfigError(
            f"cannot parse {v!r} as {ftype.__name__} for '{path}'"
        ) from e
    return v


def _build_dataclass(cls: type, data: dict[str, Any], path: str) -> Any:
    import typing
    hints = typing.get_type_hints(cls)  # resolve string annotations
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    extra: dict[str, Any] = {}
    for k, v in data.items():
        if k in fields:
            ftype = hints.get(k, fields[k].type)
            if dataclasses.is_dataclass(ftype) and isinstance(v, dict):
                v = _build_dataclass(ftype, v, f"{path}.{k}")
            elif isinstance(ftype, type):
                v = _coerce_scalar(ftype, v, f"{path}.{k}")
            kwargs[k] = v
        else:
            extra[k] = v
    if extra:
        if "kwargs" in fields:  # open-schema dataclasses (ModelConfig)
            kwargs.setdefault("kwargs", {})
            kwargs["kwargs"] = {**extra, **kwargs["kwargs"]}
        else:
            raise ConfigError(
                f"unknown key(s) {sorted(extra)} under '{path}' for "
                f"{cls.__name__}")
    return cls(**kwargs)


def config_from_dict(tree: dict[str, Any]) -> Config:
    cfg = Config(
        train=_build_dataclass(TrainConfig, tree.get("train", {}), "train"),
        mesh=_build_dataclass(MeshConfig, tree.get("mesh", {}), "mesh"),
        model=_build_dataclass(ModelConfig, tree.get("model", {}), "model"),
        run=_build_dataclass(RunConfig, tree.get("run", {}), "run"),
    )
    return cfg


def load_config(config_dir: str | None = None, config_name: str = "config",
                overrides: list[str] | None = None) -> Config:
    """Load the typed framework config.

    ``config_dir`` defaults to ``<repo_root>/conf`` (parity with the
    reference's ``@hydra.main(config_path="../conf")``,
    src/distributed_trainer.py:243).
    """
    if config_dir is None:
        config_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "conf")
    base = Config().to_dict()
    # ModelConfig's open kwargs dict is presentation-only; model YAMLs
    # write hyperparameters at the top level of the model group.
    base["model"].pop("kwargs", None)
    tree = compose(config_dir, config_name, overrides, base_tree=base)
    cfg = config_from_dict(tree)
    # Anchor snapshot_path against output_dir at load time (not at save
    # time, and with no per-run chdir) so restarts launched the same way
    # find the previous snapshot — the reference's relative "snapshot.pt"
    # + Hydra per-run chdir made resume impossible (SURVEY.md §8 B2).
    # A relative output_dir still depends on the launch cwd; launchers
    # that need cwd-independence should set an absolute run.output_dir.
    if cfg.train.snapshot_path and not os.path.isabs(cfg.train.snapshot_path):
        cfg.train.snapshot_path = os.path.abspath(
            os.path.join(cfg.run.output_dir, cfg.run.experiment_name,
                         cfg.train.snapshot_path))
    return cfg


def save_resolved(cfg: Config, path: str) -> None:
    """Write the resolved config next to run outputs for reproducibility."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(cfg.to_dict(), f, sort_keys=False)


def override_config(cfg: Config, **groups: dict[str, Any]) -> Config:
    """Return a copy of ``cfg`` with dataclass-level replacements applied
    (programmatic analogue of CLI overrides, used by tests/benches)."""
    cfg = copy.deepcopy(cfg)
    for group, repl in groups.items():
        sub = getattr(cfg, group)
        for k, v in repl.items():
            if not hasattr(sub, k):
                raise ConfigError(f"unknown field {group}.{k}")
            setattr(sub, k, v)
    return cfg
