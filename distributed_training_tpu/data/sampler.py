"""Deterministic distributed sampling with torch-DistributedSampler
semantics.

The reference shards its dataset with
``DistributedSampler(dataset)`` + ``sampler.set_epoch(epoch)``
(src/distributed_trainer.py:204-211,175; src/playground/ddp_script.py:
124-132). Its contract, reproduced here exactly (SURVEY.md §7 "hard
parts" — DistributedSampler fidelity):

- ``num_samples = ceil(N / num_shards)``; ``total = num_samples * num_shards``
- shuffle: permutation of ``range(N)`` seeded by ``seed + epoch``
  (identical on every process — no cross-host communication needed)
- padding: indices wrap around (``indices += indices[:total - N]``)
- shard ``s`` takes ``indices[s::num_shards]`` (strided, as torch does)

The RNG is NumPy's PCG64 rather than torch's MT19937, so *which*
permutation a given seed yields differs from torch — the semantics
(identical across processes, reshuffled per epoch) are what parity
requires. ``drop_last=True`` matches torch's variant (drops the tail so
every shard has ``floor(N / num_shards)`` samples).
"""

from __future__ import annotations

import numpy as np


def epoch_permutation(seed: int, epoch: int, n: int,
                      shuffle: bool = True,
                      stream: int = 0) -> np.ndarray:
    """Deterministic per-epoch permutation of ``range(n)`` as a PURE
    FUNCTION of ``(seed, stream, epoch)`` — the counter-keyed RNG
    discipline the streaming pipeline (data/stream.py) is built on:
    position is always ``(integers, cursor)``, never a live generator
    object, so pipeline state serializes into a checkpoint. ``stream``
    namespaces independent sequences (one per mixture source) under
    one seed."""
    if not shuffle:
        return np.arange(n)
    rng = np.random.default_rng([seed, stream, epoch])
    return rng.permutation(n)


class DistributedShardSampler:
    """Yields per-shard index arrays for one epoch."""

    def __init__(self, dataset_size: int, num_shards: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False) -> None:
        if dataset_size <= 0:
            raise ValueError(f"dataset_size must be > 0, got {dataset_size}")
        if num_shards <= 0:
            raise ValueError(f"num_shards must be > 0, got {num_shards}")
        self.dataset_size = dataset_size
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_size // num_shards
            if self.num_samples == 0:
                raise ValueError(
                    f"drop_last with {num_shards} shards leaves no samples "
                    f"from dataset of {dataset_size}")
        else:
            self.num_samples = -(-dataset_size // num_shards)  # ceil
        self.total_size = self.num_samples * self.num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle for a new epoch (parity:
        src/distributed_trainer.py:175)."""
        self.epoch = epoch

    def global_indices(self) -> np.ndarray:
        """The epoch's full index order before sharding, padded/truncated
        to ``total_size``."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if self.drop_last:
            return indices[:self.total_size]
        pad = self.total_size - self.dataset_size
        if pad > 0:
            reps = -(-pad // self.dataset_size)
            indices = np.concatenate(
                [indices] + [indices] * reps)[:self.total_size]
        return indices

    def shard_indices(self, shard: int) -> np.ndarray:
        """Index array for one shard (torch's ``indices[rank::world]``)."""
        if not 0 <= shard < self.num_shards:
            raise ValueError(f"shard {shard} out of range "
                             f"[0, {self.num_shards})")
        return self.global_indices()[shard::self.num_shards]

    def __len__(self) -> int:
        return self.num_samples
