"""Corpus preparation: real text files → flat binary token shard.

The real-data layout the framework trains from is a flat binary file of
token ids on (shared) storage, windowed by ``MemmapTokenDataset``
(datasets.py) — the standard pretraining shard format. This tool builds
one from ANY local text:

- ``bytes`` mode (default): raw UTF-8 bytes, vocab 256, uint8 storage.
  Zero external dependencies — subword tokenizers need downloaded vocab
  files; bytes need nothing — which makes it the hermetic real-data
  path for tests/benches as well as a legitimate byte-LM recipe.
- ``tokens`` mode: pass-through for corpora you already tokenized
  elsewhere (any integer .npy), stored uint16/uint32 as the vocab
  requires.

A ``<out>.json`` sidecar records vocab/dtype/provenance so configs can
sanity-check what they're training on.

The reference has no data-prep tooling at all (its corpus is
``torch.rand``, src/data_utils.py:7-16); this exists because
BASELINE.json config 3 targets a real tokenized shard.

Usage:
    python -m distributed_training_tpu.data.prepare \
        --out /data/corpus.bin 'src/**/*.py' docs/*.md
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import sys

import numpy as np


def collect_files(patterns: list[str]) -> list[str]:
    files: list[str] = []
    for pat in patterns:
        matches = sorted(glob.glob(pat, recursive=True))
        files.extend(m for m in matches if os.path.isfile(m))
    # de-dup, keep order
    seen: set[str] = set()
    out = []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def prepare_bytes(out_path: str, inputs: list[str],
                  separator: bytes = b"\n\n") -> dict:
    """Concatenate files as raw bytes into ``out_path`` (uint8)."""
    files = collect_files(inputs)
    if not files:
        raise FileNotFoundError(f"no files matched {inputs}")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    sha = hashlib.sha256()
    total = 0
    with open(out_path, "wb") as out:
        for i, f in enumerate(files):
            with open(f, "rb") as src:
                blob = src.read()
            if i:
                out.write(separator)
                sha.update(separator)
                total += len(separator)
            out.write(blob)
            sha.update(blob)
            total += len(blob)
    meta = {
        "mode": "bytes",
        "dtype": "uint8",
        "vocab_size": 256,
        "n_tokens": total,
        "n_files": len(files),
        "sha256": sha.hexdigest(),
    }
    with open(out_path + ".json", "w") as f:
        json.dump(meta, f, indent=2)
    return meta


def prepare_tokens(out_path: str, inputs: list[str],
                   vocab_size: int) -> dict:
    """Concatenate pre-tokenized .npy arrays into a flat binary."""
    files = collect_files(inputs)
    if not files:
        raise FileNotFoundError(f"no files matched {inputs}")
    dtype = "uint16" if vocab_size <= 2 ** 16 else "uint32"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    total = 0
    with open(out_path, "wb") as out:
        for f in files:
            arr = np.load(f)
            if arr.min() < 0 or arr.max() >= vocab_size:
                raise ValueError(
                    f"{f}: token ids outside [0, {vocab_size})")
            blob = np.ascontiguousarray(arr.reshape(-1), dtype=dtype)
            out.write(blob.tobytes())
            total += blob.size
    meta = {
        "mode": "tokens",
        "dtype": dtype,
        "vocab_size": vocab_size,
        "n_tokens": total,
        "n_files": len(files),
    }
    with open(out_path + ".json", "w") as f:
        json.dump(meta, f, indent=2)
    return meta


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("inputs", nargs="+",
                   help="files / glob patterns (recursive ** ok)")
    p.add_argument("--out", required=True, help="output .bin path")
    p.add_argument("--mode", choices=("bytes", "tokens"),
                   default="bytes")
    p.add_argument("--vocab-size", type=int, default=50257,
                   help="tokens mode: vocabulary bound for validation")
    args = p.parse_args(argv)
    if args.mode == "bytes":
        meta = prepare_bytes(args.out, args.inputs)
    else:
        meta = prepare_tokens(args.out, args.inputs, args.vocab_size)
    print(json.dumps(meta))
    return 0


if __name__ == "__main__":
    sys.exit(main())
