"""Data layer: datasets, deterministic distributed sampling, batch assembly.

TPU-native replacement for the reference's ``MyTrainDataset`` +
``DataLoader(DistributedSampler)`` stack (reference: src/data_utils.py:7-16,
src/distributed_trainer.py:204-211). Sampling semantics (shard-by-rank,
epoch-seeded reshuffle, wrap-padding to a world-size multiple) are preserved;
batch assembly produces globally-sharded ``jax.Array``s laid out for the
mesh's data axes instead of per-rank host tensors.
"""

from distributed_training_tpu.data.datasets import (  # noqa: F401
    ArrayDataset,
    Dataset,
    SyntheticLMDataset,
    SyntheticRegressionDataset,
    build_dataset,
)
from distributed_training_tpu.data.loader import (  # noqa: F401
    ShardedDataLoader,
)
from distributed_training_tpu.data.sampler import (  # noqa: F401
    DistributedShardSampler,
)
from distributed_training_tpu.data.stream import (  # noqa: F401
    StreamSource,
    StreamState,
    StreamingDataLoader,
    build_stream_sources,
)
