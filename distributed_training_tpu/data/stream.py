"""Exactly-once streaming pipeline: deterministic, resumable, multi-source.

ROADMAP item 5, the data half of the resilience story. The resilience
stack can restart a crashed run bit-identically and resize the world
without losing the run — but until this module the *data* position was
not part of the checkpoint: a mid-epoch preemption replayed the
interrupted epoch from its start (the optimizer saw the same samples
twice) and an elastic shrink re-dealt the sampler's strided shards
mid-epoch (survivors skipped and duplicated arbitrary rows). Here the
entire pipeline position is a small serializable ``StreamState`` and
every consumption decision is a pure function of it:

- **Per-source order**: source ``i``'s pass ``e`` reads its rows in
  ``epoch_permutation(seed, e, n_i, stream=i)`` order — a counter-keyed
  permutation recomputed from integers, never a live RNG object, so
  position serializes as ``(epoch, cursor)`` per source.
- **Mixture**: the source feeding global document ``d`` is chosen by
  deficit round-robin over the per-source consumed counts (pick the
  source with the largest ``weight_i * (d+1) - consumed_i``), so the
  realized mixture is deterministic from the cursors alone — it rides
  the checkpoint for free and never drifts on restart.
- **Packing**: documents concatenate into fixed blocks of
  ``pack_len + 1`` tokens (the ``+1`` keeps the next-token shift the
  LM datasets already use). A block boundary can land mid-document;
  the carry is stored as a POINTER ``(source, epoch, pos, offset)``
  into the deterministic stream — restore re-reads the document and
  skips the consumed prefix, so no tokens ride the checkpoint.
- **Sharding**: packed sample ``s`` is row ``s % global_batch`` of
  step ``s // global_batch``; shard ``k`` owns rows
  ``[k*b, (k+1)*b)`` of each step — a pure function of
  ``(state, world_size)``. With a world-size-invariant global batch
  (``train.global_batch_size``), an elastic resize re-deals only the
  not-yet-consumed remainder: the union of samples consumed across
  incarnations is the uninterrupted stream, each sample exactly once.

**Exactly-once contract**: for any save point and any world-size
history, concatenating the batches consumed across incarnations yields
the identical token stream an uninterrupted run produces — no sample
replayed, no sample skipped (deliberate ``policy=skip`` corrupt-sample
skips are *recorded*: a ``data_skip`` event with ``(source,
sample_id)``, counted in ``StreamState.skipped``). The trainer embeds
``state_dict()`` in every checkpoint's meta (committed under the same
sha256 manifest as the weights) and restores it before the first
batch; docs/data.md specifies the schema.

Failure policy at read time: transient ``OSError``s retry with backoff
(same budget as ShardedDataLoader); a sample raising an exception that
carries ``corrupt_policy == "skip"`` (``CorruptSampleError``, or the
injected ``data_corrupt`` fault) is recorded and skipped; any other
error — including ``corrupt_policy == "fatal"`` — propagates and kills
the run (the supervisor's restart will resume after the last good
checkpoint, and the one-shot fault ledger keeps injected corruption
from re-firing).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Iterator, Mapping, Sequence

import jax
import numpy as np

from distributed_training_tpu import telemetry
from distributed_training_tpu.data.loader import (_prefetch,
                                                  retry_transient)
from distributed_training_tpu.data.sampler import epoch_permutation
from distributed_training_tpu.runtime import Runtime

logger = logging.getLogger(__name__)

STATE_SCHEMA = 1

# Consecutive skip-and-record corrupt samples before the stream gives
# up and escalates to a fatal error: pervasive corruption (a rotted
# shard where EVERY read fails validation) must surface as a loud
# incident, not an infinite cursor spin that only the hang watchdog
# ever notices.
MAX_CONSECUTIVE_SKIPS = 64


class StreamStateError(ValueError):
    """A checkpointed stream state this loader cannot drive (schema or
    source-set mismatch). The trainer falls back to an epoch-boundary
    resume instead of guessing a position."""


class CorruptSampleError(ValueError):
    """A sample that failed validation at read time. Carries the
    recovery policy the stream applies: ``"skip"`` → record a
    ``data_skip`` event (source, sample_id) and continue; ``"fatal"``
    → propagate. Deliberately NOT an OSError: corrupt bytes do not
    improve on a retry. The injected ``data_corrupt`` fault
    (resilience/faults.py) raises a duck-type-compatible exception
    (same ``corrupt_policy`` attribute) so the injected path IS the
    real skip/fatal path."""

    def __init__(self, msg: str, policy: str = "skip"):
        super().__init__(msg)
        self.corrupt_policy = policy


@dataclass(frozen=True)
class StreamSource:
    """One named source in the mixture. ``weight`` is relative; the
    realized mixture converges to ``weight / sum(weights)`` in
    documents consumed."""

    name: str
    dataset: object
    weight: float = 1.0


class StreamState:
    """The ENTIRE pipeline position, serializable as a small dict.

    ``step`` counts optimizer batches fully consumed, ``samples``
    counts packed rows emitted (``samples == step * global_batch`` at
    every batch boundary), ``epochs[i]``/``cursors[i]`` are source
    ``i``'s pass count and position within its current permutation,
    ``carry`` points at a partially packed document, ``skipped``
    counts corrupt samples deliberately skipped (and recorded)."""

    def __init__(self, seed: int, names: Sequence[str],
                 sizes: Sequence[int] | None = None):
        self.seed = int(seed)
        self.names = tuple(names)
        # Source sizes are part of the stream identity too: the
        # permutation of pass e is epoch_permutation(seed, e, n), so a
        # corpus that grew or shrank across a restart is a DIFFERENT
        # stream (from_dict rejects the mismatch).
        self.sizes = tuple(int(s) for s in sizes) if sizes else None
        self.step = 0
        self.samples = 0
        self.skipped = 0
        self.epochs = [0] * len(self.names)
        self.cursors = [0] * len(self.names)
        self.carry: dict | None = None

    def clone(self) -> "StreamState":
        out = StreamState(self.seed, self.names, self.sizes)
        out.assign(self)
        return out

    def assign(self, other: "StreamState") -> None:
        """In-place copy (the retry path rolls a working state back to
        its pre-batch snapshot without rebinding closures)."""
        self.seed = other.seed
        self.names = other.names
        self.sizes = other.sizes
        self.step = other.step
        self.samples = other.samples
        self.skipped = other.skipped
        self.epochs = list(other.epochs)
        self.cursors = list(other.cursors)
        self.carry = dict(other.carry) if other.carry else None

    def to_dict(self) -> dict:
        """Checkpoint form — JSON-serializable, name-keyed (a source
        set that changed across restarts fails loudly in
        ``from_dict``, never silently misaligns cursors)."""
        return {
            "schema": STATE_SCHEMA,
            "impl": "stream",
            "seed": self.seed,
            "step": self.step,
            "samples_consumed": self.samples,
            "skipped": self.skipped,
            "sources": {
                name: {"epoch": self.epochs[i],
                       "cursor": self.cursors[i],
                       "size": self.sizes[i] if self.sizes else None}
                for i, name in enumerate(self.names)},
            "carry": dict(self.carry) if self.carry else None,
        }

    @classmethod
    def from_dict(cls, d: Mapping, seed: int, names: Sequence[str],
                  sizes: Sequence[int] | None = None) -> "StreamState":
        if d.get("schema") != STATE_SCHEMA or d.get("impl") != "stream":
            raise StreamStateError(
                f"unsupported stream state (schema={d.get('schema')!r}, "
                f"impl={d.get('impl')!r})")
        saved = d.get("sources") or {}
        # ORDER matters, not just the set: the source index keys each
        # source's permutation stream and breaks mixture ties, so a
        # reordered config is a DIFFERENT stream — restoring cursors
        # (or the positional carry) into it would silently splice
        # wrong documents.
        if list(saved) != list(names):
            raise StreamStateError(
                f"checkpointed sources {list(saved)} != configured "
                f"{list(names)} — the mixture (or its order, which "
                "keys the per-source permutation streams) changed; "
                "cursors cannot be mapped")
        if int(d.get("seed", seed)) != int(seed):
            raise StreamStateError(
                f"checkpointed stream seed {d.get('seed')} != configured "
                f"{seed} — the permutations would diverge")
        if sizes:
            for name, n in zip(names, sizes):
                saved_n = saved[name].get("size")
                if saved_n is not None and int(saved_n) != int(n):
                    raise StreamStateError(
                        f"source {name!r} changed size {saved_n} -> "
                        f"{n} across restart — its permutations "
                        "diverge; cursors cannot be mapped")
        st = cls(seed, names, sizes)
        st.step = int(d.get("step", 0))
        st.samples = int(d.get("samples_consumed", 0))
        st.skipped = int(d.get("skipped", 0))
        for i, name in enumerate(st.names):
            st.epochs[i] = int(saved[name]["epoch"])
            st.cursors[i] = int(saved[name]["cursor"])
        carry = d.get("carry")
        st.carry = dict(carry) if carry else None
        return st


def pick_source(weights: Sequence[float],
                consumed: Sequence[int]) -> int:
    """Deficit round-robin: the source owed the most documents at this
    point of the stream. A pure function of the cursors, so the
    mixture schedule checkpoints with them; ties break to the lowest
    index (stable under restart by construction)."""
    total = sum(consumed) + 1
    wsum = sum(weights)
    best, best_deficit = 0, None
    for i, (w, c) in enumerate(zip(weights, consumed)):
        deficit = (w / wsum) * total - c
        if best_deficit is None or deficit > best_deficit:
            best, best_deficit = i, deficit
    return best


def _doc_tokens(dataset, row: int) -> np.ndarray:
    """One document's tokens. Ragged datasets expose ``doc(i)``;
    fixed-row datasets serve through the columnar ``batch``."""
    if hasattr(dataset, "doc"):
        return np.asarray(dataset.doc(row))
    return np.asarray(dataset.batch(np.array([row]))["tokens"][0])


class StreamingDataLoader:
    """Multi-source exactly-once loader with the ShardedDataLoader
    interface (``steps_per_epoch``/``global_batch``/``epoch()``), so
    the Trainer drives either interchangeably.

    Every host materializes the same deterministic global batch and
    hands its devices their rows via ``make_array_from_callback`` —
    content depends only on ``(sources, seed, pack_len, global
    batch)``, never on the world size, which is what makes the elastic
    resize exactly-once. ``batch_size`` is per data shard (derive it
    from a world-size-invariant ``train.global_batch_size`` for
    elastic runs).

    An "epoch" is a bookkeeping window of ``steps_per_epoch`` batches
    over the endless stream (sources rewind per-source with fresh
    permutations), defaulting to one nominal pass: ``total_docs //
    global_batch``.
    """

    def __init__(self, sources: Sequence[StreamSource], runtime: Runtime,
                 batch_size: int, pack_len: int = 0, shuffle: bool = True,
                 seed: int = 0, steps_per_epoch: int = 0,
                 prefetch_depth: int = 2, data_retries: int = 2,
                 fault_injector=None):
        if not sources:
            raise ValueError("StreamingDataLoader needs >= 1 source")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        names = [s.name for s in sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate source names: {names}")
        for s in sources:
            if s.weight <= 0:
                raise ValueError(
                    f"source {s.name!r} weight must be > 0, got {s.weight}")
            if len(s.dataset) <= 0:
                raise ValueError(f"source {s.name!r} dataset is empty")
        self.sources = tuple(sources)
        self.runtime = runtime
        self.batch_size = batch_size
        self.num_shards = runtime.data_shard_count
        self.global_batch = batch_size * self.num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.pack_len = int(pack_len)
        if self.pack_len < 0:
            raise ValueError(f"pack_len must be >= 0, got {pack_len}")
        # Row shape: pack_len+1 tokens packed, else the (uniform)
        # source row length — probing one document per source only in
        # the unpacked mode that needs it (a probe is a real read on
        # a remote/memmap corpus). Ragged sources require packing —
        # without it there is no fixed batch shape to emit.
        if self.pack_len:
            self.block_len = self.pack_len + 1
        else:
            ragged = [s.name for s in self.sources
                      if hasattr(s.dataset, "doc")]
            if ragged:
                # The ``doc()`` protocol declares per-row lengths may
                # vary — a doc-0 probe can't prove uniformity, and a
                # mid-run length mismatch would be a deterministic
                # crash loop (the permutation replays to the same odd
                # doc every restart). Fail at construction instead.
                raise ValueError(
                    f"source(s) {ragged} are ragged (expose doc()); "
                    "without packing there is no fixed batch shape — "
                    "set train.pack_seq_len")
            lens = {s.name: len(_doc_tokens(s.dataset, 0))
                    for s in self.sources}
            if len(set(lens.values())) != 1:
                raise ValueError(
                    "without packing (pack_len=0) every source must "
                    f"yield equal-length rows; got {lens} — set "
                    "train.pack_seq_len to pack mixed lengths")
            self.block_len = next(iter(lens.values()))
        total_docs = sum(len(s.dataset) for s in self.sources)
        self.steps_per_epoch = max(
            1, steps_per_epoch or total_docs // self.global_batch)
        self.prefetch_depth = prefetch_depth
        self.data_retries = data_retries
        self._faults = fault_injector
        # Per-source permutation cache {src: {epoch: perm}} — see
        # _row_at. Derived data only; never serialized.
        self._perms: dict[int, dict[int, np.ndarray]] = {}
        # In-memory tokens of the carried (partially packed) document,
        # keyed by its carry pointer — the pointer alone is what
        # serializes; this cache just avoids re-reading the straddling
        # document at every block boundary (a ~2x read amplification
        # on short docs). Keyed lookups make rollback/restore
        # staleness self-resolving.
        self._carry_toks: tuple[tuple[int, int, int], np.ndarray] | None \
            = None
        self.state = StreamState(seed, names, self._sizes())
        vocabs = [getattr(s.dataset, "vocab_size", None)
                  for s in self.sources]
        vocabs = [v for v in vocabs if v]
        self.dataset = _StreamProbe(
            total_docs, self.block_len,
            vocab_size=max(vocabs) if vocabs else None)

    def _sizes(self) -> list[int]:
        return [len(s.dataset) for s in self.sources]

    # -- checkpointable position -------------------------------------------

    def state_dict(self) -> dict:
        """The pipeline position + the mixture evidence the resume
        telemetry event carries (realized vs target, derived from the
        cursors — nothing here is sampled at save time)."""
        d = self.state.to_dict()
        d["realized_mixture"] = self.realized_mixture()
        d["target_mixture"] = self.target_mixture()
        d["mid_epoch"] = self.state.step % self.steps_per_epoch != 0
        d["global_batch"] = self.global_batch
        d["shuffle"] = self.shuffle
        return d

    def load_state_dict(self, d: Mapping) -> None:
        if d.get("shuffle") not in (None, self.shuffle):
            # Same failure class as a seed change: shuffle toggles
            # every per-source permutation between shuffled and
            # arange, so cursors (and the carry pointer) would index
            # a different stream.
            raise StreamStateError(
                f"checkpointed shuffle={d.get('shuffle')} != "
                f"configured {self.shuffle} — the permutations "
                "diverge; cursors cannot be mapped")
        saved_gb = d.get("global_batch")
        if saved_gb not in (None, self.global_batch):
            # step/samples count in units of the global batch; a
            # different global batch (legacy per-shard batch_size
            # under an elastic resize) makes the cursors — and the
            # documented samples == step * global_batch invariant —
            # unit-incoherent. Reject; the trainer falls back to the
            # honest epoch-boundary resume. Elastic runs preserve the
            # global batch via train.global_batch_size, which keeps
            # this invariant across any world size.
            raise StreamStateError(
                f"checkpointed global batch {saved_gb} != configured "
                f"{self.global_batch} — the stream's step/sample units "
                "diverge; set train.global_batch_size for elastic runs")
        self.state = StreamState.from_dict(
            d, self.seed, [s.name for s in self.sources],
            self._sizes())

    @property
    def resume_epoch(self) -> int:
        """The epoch the current position falls in — what the trainer
        resumes INTO (mid-epoch positions land inside it)."""
        return self.state.step // self.steps_per_epoch

    def seek_epoch(self, epoch: int) -> None:
        """Fast-forward to an epoch boundary by replaying the stream's
        reads — the resume fallback when a checkpoint carries no
        usable stream state. Documents are re-read (so real
        corrupt-sample skips replay and the cursors land exactly where
        the consuming incarnation left them) but nothing is
        materialized or emitted, and injected faults NEVER fire — the
        replay consumes nothing; a stall/corruption here would be
        charged to samples a previous incarnation already trained on.
        Cannot rewind: the stream is forward-only by construction."""
        target = epoch * self.steps_per_epoch
        if target < self.state.step:
            raise StreamStateError(
                f"cannot seek backwards (step {self.state.step} -> "
                f"{target}); rebuild the loader instead")
        work = self.state.clone()
        pre_seek_skipped = work.skipped
        faults, self._faults = self._faults, None
        try:
            # Replay by actually reading (both modes): a pure-cursor
            # fast-forward would land short of the consumed position
            # whenever the original incarnation skip-and-recorded
            # corrupt samples — their cursor advances only replay if
            # the reads (and their skips) replay too. Those skips
            # were already recorded by the incarnation that consumed
            # them: collect into a throwaway buffer (no events) and
            # restore the counter below.
            discard: list[dict] = []
            while work.step < target:
                for _ in range(self.global_batch):
                    self._next_block(work, work.step + 1, discard)
                work.samples += self.global_batch
                work.step += 1
        finally:
            self._faults = faults
        work.skipped = pre_seek_skipped
        self.state = work

    def realized_mixture(self) -> dict[str, float]:
        counts = self._doc_counts(self.state)
        total = sum(counts) or 1
        return {s.name: round(c / total, 6)
                for s, c in zip(self.sources, counts)}

    def target_mixture(self) -> dict[str, float]:
        wsum = sum(s.weight for s in self.sources)
        return {s.name: round(s.weight / wsum, 6) for s in self.sources}

    # -- the deterministic stream ------------------------------------------

    def _doc_counts(self, state: StreamState) -> list[int]:
        return [state.epochs[i] * len(s.dataset) + state.cursors[i]
                for i, s in enumerate(self.sources)]

    def _row_at(self, src: int, epoch: int, pos: int) -> int:
        # Permutations are pure functions of (seed, src, epoch) but
        # O(n) to build — computing one per DOCUMENT would make a
        # source pass O(n^2). Cache per source, keeping the two
        # newest epochs (the carry may still point one epoch back).
        # Only the producer thread (or seek, with no producer live)
        # reads documents, so no locking is needed.
        cache = self._perms.setdefault(src, {})
        perm = cache.get(epoch)
        if perm is None:
            perm = epoch_permutation(self.seed, epoch,
                                     len(self.sources[src].dataset),
                                     shuffle=self.shuffle, stream=src)
            cache[epoch] = perm
            for e in sorted(cache)[:-2]:
                del cache[e]
        return int(perm[pos])

    def _advance_cursor(self, state: StreamState) -> tuple[int, int]:
        """Pick the next source and advance its cursor — the pure
        integer core every consumption decision reduces to. Returns
        ``(source index, row id)``."""
        src = pick_source([s.weight for s in self.sources],
                          self._doc_counts(state))
        epoch, pos = state.epochs[src], state.cursors[src]
        row = self._row_at(src, epoch, pos)
        state.cursors[src] += 1
        if state.cursors[src] >= len(self.sources[src].dataset):
            state.cursors[src] = 0
            state.epochs[src] += 1
        return src, row

    def _read_doc(self, state: StreamState, src: int, row: int,
                  fault_step: int, skips: list | None,
                  cached: np.ndarray | None = None
                  ) -> np.ndarray | None:
        """One document read under the full failure policy: the
        source-level fault hook fires first (so injected stalls and
        corruption hit every read path, carried documents included),
        then the skip-and-record handling — ``None`` means "this
        sample was recorded as skipped; move on". Skip records
        collect into ``skips`` so the caller emits them only once the
        batch COMMITS — emitting inside the retried block would
        double-count a skip whose batch is rolled back by a later
        transient error."""
        name = self.sources[src].name
        try:
            if self._faults is not None:
                self._faults.on_source(fault_step, name)
            if cached is not None:
                return cached
            return _doc_tokens(self.sources[src].dataset, row)
        except ValueError as e:
            policy = getattr(e, "corrupt_policy", "fatal")
            if policy != "skip":
                raise
            # Exactly-once accounting for the skip: the sample is
            # RECORDED (event + counter), never silently dropped.
            state.skipped += 1
            record = dict(source=name, sample_id=row, step=fault_step,
                          error=f"{type(e).__name__}: {e}")
            if skips is None:
                telemetry.event("data_skip", **record)
            else:
                skips.append(record)
            logger.warning(
                "skipping corrupt sample %s[%d] at step %d: %s",
                name, row, fault_step, e)
            return None

    def _next_doc(self, state: StreamState, fault_step: int,
                  skips: list | None = None
                  ) -> tuple[int, int, np.ndarray]:
        """Pull the next document — advancing cursors under the
        ``_read_doc`` failure policy, with a bound on consecutive
        skips (pervasive corruption must surface as an incident, not
        an infinite cursor spin)."""
        consecutive = 0
        while True:
            src, row = self._advance_cursor(state)
            toks = self._read_doc(state, src, row, fault_step, skips)
            if toks is not None:
                return src, row, toks
            consecutive += 1
            if consecutive > MAX_CONSECUTIVE_SKIPS:
                raise ValueError(
                    f"{consecutive} consecutive corrupt samples "
                    f"(last: {self.sources[src].name}[{row}]) — "
                    "pervasive corruption is an incident, not "
                    "something to skip past")

    def _next_block(self, state: StreamState, fault_step: int,
                    skips: list | None = None) -> np.ndarray:
        """One fixed-shape sample row: a whole document, or a packed
        ``block_len`` window continuing from the carry pointer."""
        if not self.pack_len:
            _src, _row, toks = self._next_doc(state, fault_step, skips)
            if len(toks) != self.block_len:
                raise ValueError(
                    f"unpacked row length {len(toks)} != {self.block_len}"
                    " (sources must be uniform without packing)")
            return np.asarray(toks, dtype=np.int32)
        out = np.empty((self.block_len,), dtype=np.int32)
        filled = 0
        while filled < self.block_len:
            if state.carry is not None:
                c = state.carry
                src_epoch_pos = (c["source"], c["epoch"], c["pos"])
                cached = (self._carry_toks[1]
                          if self._carry_toks is not None
                          and self._carry_toks[0] == src_epoch_pos
                          else None)
                row = self._row_at(*src_epoch_pos)
                # Same failure policy as fresh documents: the fault
                # hook fires (carry-only steps must not be a fault
                # blind spot) and a skip-policy corruption of the
                # carried doc drops its unconsumed remainder —
                # recorded — instead of crash-looping every restart
                # on the same carry pointer.
                toks = self._read_doc(state, c["source"], row,
                                      fault_step, skips, cached=cached)
                if toks is None:
                    state.carry = None
                    continue
                offset = c["offset"]
            else:
                src, _row, toks = self._next_doc(state, fault_step,
                                                 skips)
                offset = 0
                # The doc just consumed sits at cursor-1 of its
                # (possibly just-wrapped) permutation.
                pos = state.cursors[src] - 1
                epoch = state.epochs[src]
                if pos < 0:
                    pos = len(self.sources[src].dataset) - 1
                    epoch -= 1
                src_epoch_pos = (src, epoch, pos)
            take = min(len(toks) - offset, self.block_len - filled)
            out[filled:filled + take] = toks[offset:offset + take]
            filled += take
            if offset + take < len(toks):
                state.carry = {"source": src_epoch_pos[0],
                               "epoch": src_epoch_pos[1],
                               "pos": src_epoch_pos[2],
                               "offset": offset + take}
                self._carry_toks = (src_epoch_pos, toks)
            else:
                state.carry = None
        return out

    # -- batch production ---------------------------------------------------

    def _produce_step(self, work: StreamState
                      ) -> tuple[dict[str, jax.Array], StreamState,
                                 list[dict]]:
        """Assemble the next global batch, advancing ``work`` — under
        the shared ``retry_transient`` policy, with ``work`` rolled
        back to its pre-batch snapshot before each retry so a retried
        batch is bit-identical to an untried one. Returns the device
        batch, a consumed-state snapshot, and the batch's skip
        records; the CONSUMER commits all three together — emitting
        skips here (the prefetch thread, up to depth batches ahead)
        would record skips of batches a preemption never consumes,
        which the resumed incarnation then records again."""
        fault_step = work.step + 1
        snapshot = work.clone()
        skips: list[dict] = []

        def assemble():
            # A retried attempt starts from a clean slate: the
            # rollback restored ``work``; the skip buffer must reset
            # with it or a re-skipped sample double-emits.
            skips.clear()
            if self._faults is not None:
                self._faults.on_data(fault_step)
            return np.stack([self._next_block(work, fault_step, skips)
                             for _ in range(self.global_batch)])

        rows = retry_transient(assemble, retries=self.data_retries,
                               rollback=lambda: work.assign(snapshot),
                               step=fault_step)
        work.samples += self.global_batch
        work.step += 1
        sharding = self.runtime.batch_sharding
        batch = {"tokens": jax.make_array_from_callback(
            rows.shape, sharding, lambda idx: rows[idx])}
        return batch, work.clone(), list(skips)

    def epoch(self, epoch: int) -> Iterator[Mapping[str, jax.Array]]:
        """Yield this epoch's REMAINING batches, continuing from the
        current (possibly restored, mid-epoch) position. The consumed
        position commits as each batch is handed over, so a save at
        any point records exactly the batches the trainer took."""
        spe = self.steps_per_epoch
        if not epoch * spe <= self.state.step < (epoch + 1) * spe:
            raise ValueError(
                f"epoch({epoch}) does not contain stream position "
                f"step={self.state.step} (steps_per_epoch={spe}) — "
                "resume must continue from the restored cursor")
        remaining = (epoch + 1) * spe - self.state.step
        work = self.state.clone()

        def produce():
            for k in range(remaining):
                # Assemble BEFORE yield (the ShardedDataLoader
                # discipline): the generator suspends at the yield, so
                # a span around it would stay open while the consumer
                # trains and the duration would be meaningless.
                with telemetry.span(
                        "data_assemble",
                        step_in_epoch=work.step - epoch * spe):
                    item = self._produce_step(work)
                yield item

        it = (_prefetch(produce(), self.prefetch_depth)
              if self.prefetch_depth > 0 else produce())
        try:
            for batch, consumed, skips in it:
                # Commit point: position and skip evidence land
                # together, only for batches the trainer actually
                # takes (see _produce_step).
                self.state = consumed
                for record in skips:
                    telemetry.event("data_skip", **record)
                yield batch
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def __len__(self) -> int:
        return self.steps_per_epoch


class _StreamProbe:
    """Cheap stand-in for ``loader.dataset`` so the Trainer's
    model/dataset contract checks (batch keys, vocab range) work
    without touching the stream position."""

    def __init__(self, total_docs: int, block_len: int,
                 vocab_size: int | None = None):
        self._total = total_docs
        self._block_len = block_len
        if vocab_size is not None:
            # Max over sources: the contract check must catch ANY
            # source whose ids exceed the model's embedding table.
            self.vocab_size = vocab_size
        self.seq_len = block_len - 1

    def __len__(self) -> int:
        return self._total

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        return {"tokens": np.zeros((len(indices), self._block_len),
                                   dtype=np.int32)}


def build_stream_sources(specs: Mapping[str, Mapping], *,
                         defaults: Mapping | None = None
                         ) -> list[StreamSource]:
    """Sources from ``train.data_sources`` config: ``{name: {dataset:
    <registry name>, weight: W, **dataset kwargs}}``. Order follows
    the mapping (identical on every host — it comes from config)."""
    from distributed_training_tpu.data.datasets import build_dataset
    sources: list[StreamSource] = []
    for name, spec in specs.items():
        if not isinstance(spec, Mapping) or "dataset" not in spec:
            raise ValueError(
                f"train.data_sources.{name} must be a mapping with a "
                f"'dataset' key, got {spec!r}")
        kwargs = dict(spec)
        ds_name = kwargs.pop("dataset")
        weight = float(kwargs.pop("weight", 1.0))
        ds = build_dataset(ds_name, _defaults=dict(defaults or {}),
                           **kwargs)
        sources.append(StreamSource(name=name, dataset=ds,
                                    weight=weight))
    return sources
