"""Batch assembly: host rows → globally-sharded ``jax.Array`` batches.

Replaces the reference's ``prepare_dataloader`` (``DataLoader`` +
``DistributedSampler``, src/distributed_trainer.py:204-211). The torch
stack hands each process a *local* tensor; the TPU-native shape is a
single *global* ``jax.Array`` whose batch dimension is laid out over the
mesh's data axes — each process materializes only the rows its devices
own (``jax.make_array_from_callback``), so multi-host input never funnels
through one host (SURVEY.md §7 "multi-host input pipeline").

Shard → batch-row mapping: shard ``s`` (``dp``-major over ``(dp, fsdp)``,
matching how ``PartitionSpec(("dp", "fsdp"))`` partitions the batch dim)
contributes rows ``[s*b, (s+1)*b)`` of the global batch of size
``b * num_shards``.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterator, Mapping

import jax
import numpy as np

from distributed_training_tpu import telemetry
from distributed_training_tpu.data.sampler import DistributedShardSampler
from distributed_training_tpu.runtime import Runtime

logger = logging.getLogger(__name__)

# The retryable input-pipeline failure class: host-side IO blips
# (network filesystems, object stores; TimeoutError is an OSError
# subclass) and injected transients (resilience/faults.py::
# InjectedDataError subclasses OSError). A ValueError/KeyError stays
# fatal — malformed data won't improve on the second read.
TRANSIENT_DATA_ERRORS = (OSError,)


def retry_transient(assemble, *, retries: int, rollback=None,
                    **event_fields):
    """Run one batch assembly with a bounded transient-failure budget
    — THE retry policy, shared by both loaders so their recovery
    behavior cannot drift.

    A single IO blip (network filesystem hiccup, object-store 5xx)
    must not kill a step loop that a supervisor would then pay a whole
    restart-and-resume cycle for: retry ``retries`` times with short
    exponential backoff, emitting a ``data_retry`` telemetry event per
    attempt (``event_fields`` carry the caller's position vocabulary),
    then re-raise — a blip that persists IS an incident and should
    surface. ``rollback`` (if given) runs before each retry so a
    stateful assembler restarts the batch from its pre-batch snapshot
    and a retried batch is bit-identical to an untried one."""
    attempt = 0
    while True:
        try:
            return assemble()
        except TRANSIENT_DATA_ERRORS as e:
            if rollback is not None:
                rollback()
            attempt += 1
            if attempt > retries:
                raise
            delay = min(2.0, 0.05 * 2 ** (attempt - 1))
            logger.warning(
                "transient data error (attempt %d/%d, retrying "
                "in %.2fs): %s: %s", attempt, retries, delay,
                type(e).__name__, e)
            telemetry.event(
                "data_retry", attempt=attempt, retries=retries,
                backoff_s=delay, error=f"{type(e).__name__}: {e}",
                **event_fields)
            time.sleep(delay)


class ShardedDataLoader:
    """Epoch-based loader yielding dicts of globally-sharded jax.Arrays.

    ``batch_size`` is per data shard, matching the reference semantics
    where ``train.batch_size`` is per-rank (conf/train/default.yaml:1,
    README "Input batch size on each device"); the global batch is
    ``batch_size * runtime.data_shard_count``.
    """

    def __init__(self, dataset, runtime: Runtime, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False, max_steps_per_epoch: int = 0,
                 prefetch_depth: int = 2, data_retries: int = 2,
                 fault_injector=None):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        self.dataset = dataset
        self.runtime = runtime
        self.batch_size = batch_size
        self.num_shards = runtime.data_shard_count
        self.global_batch = batch_size * self.num_shards
        self.sampler = DistributedShardSampler(
            len(dataset), self.num_shards, shuffle=shuffle, seed=seed,
            drop_last=drop_last)
        # Final partial batch is wrap-padded to keep shapes static under
        # jit (a partial batch would trigger recompilation). The torch
        # DataLoader instead emits a short final batch; divergence is
        # documented in docs/parity.md.
        self.steps_per_epoch = -(-self.sampler.num_samples // batch_size)
        if max_steps_per_epoch:
            self.steps_per_epoch = min(self.steps_per_epoch,
                                       max_steps_per_epoch)
        self.prefetch_depth = prefetch_depth
        # Transient-failure budget per batch (see _assemble_with_retry)
        # and the deterministic fault hook (resilience/faults.py).
        self.data_retries = data_retries
        self._faults = fault_injector
        # Checkpointable position (exactly-once contract, docs/data.md):
        # (epoch, batches CONSUMED within it) — committed as the
        # consumer takes each batch, so a save at any loop point
        # records exactly what the optimizer has seen. ``_resume``
        # holds a restored position until the matching epoch() call
        # picks it up mid-epoch.
        self._position = (0, 0)
        self._resume: tuple[int, int] | None = None
        # Column names/shapes/dtypes, learned from the first probe and
        # cached — re-probing row 0 every step doubles IO on a
        # remote/memmap source for information that cannot change.
        self._col_spec: dict | None = None

    # -- checkpointable position -------------------------------------------

    def state_dict(self) -> dict:
        """Serializable pipeline position (rides checkpoint meta under
        the integrity manifest). ``samples_consumed`` counts global
        rows handed to the trainer — what the recovery table's
        replayed/skipped columns are derived from."""
        epoch, step = self._position
        if step >= self.steps_per_epoch:
            epoch, step = epoch + 1, 0
        return {
            "schema": 1,
            "impl": "sharded",
            "seed": self.sampler.seed,
            "epoch": epoch,
            "step_in_epoch": step,
            "steps_per_epoch": self.steps_per_epoch,
            "num_shards": self.num_shards,
            "batch_size": self.batch_size,
            "shuffle": self.sampler.shuffle,
            "samples_consumed": (epoch * self.steps_per_epoch + step)
            * self.global_batch,
            # Lets the resume fallback distinguish "mid-epoch save
            # with unusable offset" (replay the epoch) from "epoch
            # boundary" (start the next) without trusting the offset.
            "mid_epoch": step > 0,
        }

    def load_state_dict(self, d) -> None:
        if d.get("schema") != 1 or d.get("impl") != "sharded":
            raise ValueError(
                f"unsupported loader state (schema={d.get('schema')!r}, "
                f"impl={d.get('impl')!r})")
        if d.get("shuffle") not in (None, self.sampler.shuffle):
            # shuffle=True/False pick different per-epoch orders (a
            # permutation vs arange) — same failure class as a seed
            # change: the offset would index a different stream.
            raise ValueError(
                f"checkpointed loader shuffle={d.get('shuffle')} != "
                f"configured {self.sampler.shuffle} — the epoch orders "
                "diverge; positions are not transferable")
        if int(d.get("seed", self.sampler.seed)) != self.sampler.seed:
            # A changed seed reshuffles every epoch: resuming mid-epoch
            # at the saved OFFSET of a different permutation would
            # silently skip/replay rows while the cursor math still
            # claims exactly-once. Fail; the trainer falls back to an
            # epoch-boundary resume (honest: the replay count shows).
            raise ValueError(
                f"checkpointed loader seed {d.get('seed')} != "
                f"configured {self.sampler.seed} — the permutations "
                "diverge; mid-epoch offsets are not transferable")
        epoch, step = int(d["epoch"]), int(d["step_in_epoch"])
        for field_name, current in (
                ("steps_per_epoch", self.steps_per_epoch),
                ("num_shards", self.num_shards),
                ("batch_size", self.batch_size)):
            saved = d.get(field_name)
            if saved not in (None, current) and step > 0:
                # Epoch geometry changed across the restart (elastic
                # world resize with the legacy strided deal, batch /
                # max_steps override): the per-epoch row->(shard,
                # step) deal is a function of all three, so the
                # mid-epoch offset no longer names the same rows —
                # even when steps_per_epoch happens to coincide.
                # Raising routes the trainer to its mid-epoch
                # fallback, which REPLAYS the interrupted epoch
                # (skipping its unconsumed remainder would silently
                # drop data; the replay count reports honestly).
                # Boundary positions (step 0) survive geometry
                # changes: epoch starts are well-defined at any world
                # size. The multi-source stream loader has no such
                # restriction — its global order is world-invariant.
                raise ValueError(
                    f"loader {field_name} changed {saved} -> {current} "
                    "across restart; the mid-epoch offset is not "
                    "transferable")
        self._position = (epoch, step)
        self._resume = (epoch, step)

    @property
    def resume_epoch(self) -> int:
        """The epoch the current position falls in (what the trainer
        resumes INTO; mid-epoch positions land inside it)."""
        epoch, step = self._position
        return epoch + 1 if step >= self.steps_per_epoch else epoch

    def seek_epoch(self, epoch: int) -> None:
        """Position the loader at an epoch boundary — the resume
        fallback for checkpoints that carry no (usable) loader state:
        epoch starts are well-defined without one because the
        per-epoch order is a pure function of ``(seed, epoch)``."""
        self._position = (epoch, 0)
        self._resume = (epoch, 0)

    def _epoch_shard_orders(self, epoch: int) -> np.ndarray:
        """(num_shards, num_samples) index matrix for this epoch, with
        per-shard wrap padding up to a batch multiple."""
        self.sampler.set_epoch(epoch)
        per_shard = np.stack([self.sampler.shard_indices(s)
                              for s in range(self.num_shards)])
        need = self.steps_per_epoch * self.batch_size
        if per_shard.shape[1] < need:
            reps = -(-need // per_shard.shape[1])
            per_shard = np.concatenate([per_shard] * (reps + 1),
                                       axis=1)[:, :need]
        return per_shard

    def _assemble(self, rows_by_shard: np.ndarray) -> dict[str, jax.Array]:
        """Build the global sharded batch from per-shard row indices."""
        sharding = self.runtime.batch_sharding
        b = self.batch_size
        if self._col_spec is None:
            # Probe one row ONCE to learn column names/shapes/dtypes
            # without materializing anything remote; the spec cannot
            # change within a dataset, so it is cached for the run.
            probe = self.dataset.batch(rows_by_shard[:1, 0])
            self._col_spec = {name: col.shape[1:]
                              for name, col in probe.items()}
        out: dict[str, jax.Array] = {}
        for name, tail in self._col_spec.items():
            global_shape = (self.global_batch,) + tuple(tail)

            def cb(index, *, _name=name):
                rows = index[0]
                start = 0 if rows.start is None else rows.start
                stop = global_shape[0] if rows.stop is None else rows.stop
                idx = np.concatenate([
                    rows_by_shard[s, :b]
                    for s in range(start // b, -(-stop // b))
                ])[start - (start // b) * b:][:stop - start]
                return self.dataset.batch(idx)[_name]

            out[name] = jax.make_array_from_callback(
                global_shape, sharding, cb)
        return out

    def _assemble_with_retry(self, rows_by_shard: np.ndarray, *,
                             epoch: int, step_in_epoch: int
                             ) -> dict[str, jax.Array]:
        """``_assemble`` under the shared ``retry_transient`` policy.

        The deterministic fault hook runs INSIDE the retried block, so
        an injected transient (``data_error@N``) exercises exactly the
        real recovery path. The hook's step key is the loader's own
        deterministic batch counter (``epoch * steps_per_epoch +
        step_in_epoch + 1``) — the optimizer's global step: since the
        restored cursor makes a resumed epoch continue at its saved
        ``step_in_epoch`` (never replay from the epoch start), the key
        is derived from the same position the checkpoint carries."""
        fault_step = epoch * self.steps_per_epoch + step_in_epoch + 1

        def assemble():
            if self._faults is not None:
                self._faults.on_data(fault_step)
            return self._assemble(rows_by_shard)

        return retry_transient(assemble, retries=self.data_retries,
                               epoch=epoch, step_in_epoch=step_in_epoch)

    def epoch(self, epoch: int) -> Iterator[Mapping[str, jax.Array]]:
        """Iterate one epoch's batches (device-sharded), with background
        host-side prefetch replacing DataLoader worker processes.

        A restored position (``load_state_dict``) makes the MATCHING
        epoch start mid-epoch at the saved batch offset — the
        exactly-once resume: the per-epoch order is a pure function of
        ``(seed, epoch, num_shards)``, so the remaining batches are
        identical to the uninterrupted run's tail. The consumed
        position commits as the consumer takes each batch; closing the
        iterator early (preemption, eviction) stops and joins the
        prefetch worker."""
        start = 0
        if self._resume is not None and self._resume[0] == epoch:
            start = min(self._resume[1], self.steps_per_epoch)
        self._resume = None
        orders = self._epoch_shard_orders(epoch)

        def produce():
            for step in range(start, self.steps_per_epoch):
                sl = slice(step * self.batch_size,
                           (step + 1) * self.batch_size)
                # Event-stream-only span (it runs in the prefetch
                # thread, concurrent with the consumer's step — the
                # goodput ledger counts only the consumer-side
                # data_wait). Assemble BEFORE yield so the span
                # doesn't stay open while the consumer trains.
                with telemetry.span("data_assemble",
                                    step_in_epoch=step):
                    batch = self._assemble_with_retry(
                        orders[:, sl], epoch=epoch, step_in_epoch=step)
                yield batch

        it = (_prefetch(produce(), self.prefetch_depth)
              if self.prefetch_depth > 0 else produce())
        step = start
        try:
            for batch in it:
                step += 1
                self._position = (epoch, step)
                yield batch
            self._position = (epoch + 1, 0)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def __len__(self) -> int:
        return self.steps_per_epoch


def _prefetch(it: Iterator, depth: int) -> Iterator:
    """Run ``it`` in a daemon thread, keeping ``depth`` items ready.

    The host-side analogue of DataLoader's worker+pin_memory pipelining
    (reference: src/distributed_trainer.py:206-208): batch assembly and
    H2D transfer overlap with device compute.

    A consumer that stops early (preemption mid-epoch, an epoch cap,
    a crash unwinding the stack) must not strand the worker blocked
    forever on ``q.put`` holding dataset/native-gather resources: the
    worker's puts are stop-aware, and the generator's ``finally``
    (run by ``close()`` or GC) signals stop, drains the queue, closes
    the producer generator, and JOINS the thread."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()
    err: list[BaseException] = []

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    return
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
            put(_END)

    t = threading.Thread(target=worker, name="data-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
        # Unblock a put-in-flight so the join below cannot hang.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)
