"""Batch assembly: host rows → globally-sharded ``jax.Array`` batches.

Replaces the reference's ``prepare_dataloader`` (``DataLoader`` +
``DistributedSampler``, src/distributed_trainer.py:204-211). The torch
stack hands each process a *local* tensor; the TPU-native shape is a
single *global* ``jax.Array`` whose batch dimension is laid out over the
mesh's data axes — each process materializes only the rows its devices
own (``jax.make_array_from_callback``), so multi-host input never funnels
through one host (SURVEY.md §7 "multi-host input pipeline").

Shard → batch-row mapping: shard ``s`` (``dp``-major over ``(dp, fsdp)``,
matching how ``PartitionSpec(("dp", "fsdp"))`` partitions the batch dim)
contributes rows ``[s*b, (s+1)*b)`` of the global batch of size
``b * num_shards``.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterator, Mapping

import jax
import numpy as np

from distributed_training_tpu import telemetry
from distributed_training_tpu.data.sampler import DistributedShardSampler
from distributed_training_tpu.runtime import Runtime

logger = logging.getLogger(__name__)

# The retryable input-pipeline failure class: host-side IO blips
# (network filesystems, object stores; TimeoutError is an OSError
# subclass) and injected transients (resilience/faults.py::
# InjectedDataError subclasses OSError). A ValueError/KeyError stays
# fatal — malformed data won't improve on the second read.
TRANSIENT_DATA_ERRORS = (OSError,)


class ShardedDataLoader:
    """Epoch-based loader yielding dicts of globally-sharded jax.Arrays.

    ``batch_size`` is per data shard, matching the reference semantics
    where ``train.batch_size`` is per-rank (conf/train/default.yaml:1,
    README "Input batch size on each device"); the global batch is
    ``batch_size * runtime.data_shard_count``.
    """

    def __init__(self, dataset, runtime: Runtime, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False, max_steps_per_epoch: int = 0,
                 prefetch_depth: int = 2, data_retries: int = 2,
                 fault_injector=None):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        self.dataset = dataset
        self.runtime = runtime
        self.batch_size = batch_size
        self.num_shards = runtime.data_shard_count
        self.global_batch = batch_size * self.num_shards
        self.sampler = DistributedShardSampler(
            len(dataset), self.num_shards, shuffle=shuffle, seed=seed,
            drop_last=drop_last)
        # Final partial batch is wrap-padded to keep shapes static under
        # jit (a partial batch would trigger recompilation). The torch
        # DataLoader instead emits a short final batch; divergence is
        # documented in docs/parity.md.
        self.steps_per_epoch = -(-self.sampler.num_samples // batch_size)
        if max_steps_per_epoch:
            self.steps_per_epoch = min(self.steps_per_epoch,
                                       max_steps_per_epoch)
        self.prefetch_depth = prefetch_depth
        # Transient-failure budget per batch (see _assemble_with_retry)
        # and the deterministic fault hook (resilience/faults.py).
        self.data_retries = data_retries
        self._faults = fault_injector

    def _epoch_shard_orders(self, epoch: int) -> np.ndarray:
        """(num_shards, num_samples) index matrix for this epoch, with
        per-shard wrap padding up to a batch multiple."""
        self.sampler.set_epoch(epoch)
        per_shard = np.stack([self.sampler.shard_indices(s)
                              for s in range(self.num_shards)])
        need = self.steps_per_epoch * self.batch_size
        if per_shard.shape[1] < need:
            reps = -(-need // per_shard.shape[1])
            per_shard = np.concatenate([per_shard] * (reps + 1),
                                       axis=1)[:, :need]
        return per_shard

    def _assemble(self, rows_by_shard: np.ndarray) -> dict[str, jax.Array]:
        """Build the global sharded batch from per-shard row indices."""
        sharding = self.runtime.batch_sharding
        b = self.batch_size
        # Probe one row to learn column names/shapes/dtypes without
        # materializing anything remote.
        probe = self.dataset.batch(rows_by_shard[:1, 0])
        out: dict[str, jax.Array] = {}
        for name, col in probe.items():
            global_shape = (self.global_batch,) + col.shape[1:]

            def cb(index, *, _name=name):
                rows = index[0]
                start = 0 if rows.start is None else rows.start
                stop = global_shape[0] if rows.stop is None else rows.stop
                idx = np.concatenate([
                    rows_by_shard[s, :b]
                    for s in range(start // b, -(-stop // b))
                ])[start - (start // b) * b:][:stop - start]
                return self.dataset.batch(idx)[_name]

            out[name] = jax.make_array_from_callback(
                global_shape, sharding, cb)
        return out

    def _assemble_with_retry(self, rows_by_shard: np.ndarray, *,
                             epoch: int, step_in_epoch: int
                             ) -> dict[str, jax.Array]:
        """``_assemble`` with a bounded transient-failure budget.

        A single IO blip (network filesystem hiccup, object-store 5xx)
        must not kill a step loop that a supervisor would then pay a
        whole restart-and-resume cycle for: retry ``data_retries``
        times with short exponential backoff, emitting a ``data_retry``
        telemetry event per attempt, then re-raise (a blip that
        persists IS an incident and should surface).

        The deterministic fault hook runs INSIDE the retried block, so
        an injected transient (``data_error@N``) exercises exactly the
        real recovery path. The hook's step key is the loader's own
        deterministic batch counter (``epoch * steps_per_epoch +
        step_in_epoch + 1`` — the optimizer's global step whenever
        epochs are replayed from their start, which is how the trainer
        resumes)."""
        fault_step = epoch * self.steps_per_epoch + step_in_epoch + 1
        attempt = 0
        while True:
            try:
                if self._faults is not None:
                    self._faults.on_data(fault_step)
                return self._assemble(rows_by_shard)
            except TRANSIENT_DATA_ERRORS as e:
                attempt += 1
                if attempt > self.data_retries:
                    raise
                delay = min(2.0, 0.05 * 2 ** (attempt - 1))
                logger.warning(
                    "transient data error (attempt %d/%d, retrying "
                    "in %.2fs): %s: %s", attempt, self.data_retries,
                    delay, type(e).__name__, e)
                telemetry.event(
                    "data_retry", attempt=attempt,
                    retries=self.data_retries, epoch=epoch,
                    step_in_epoch=step_in_epoch, backoff_s=delay,
                    error=f"{type(e).__name__}: {e}")
                time.sleep(delay)

    def epoch(self, epoch: int) -> Iterator[Mapping[str, jax.Array]]:
        """Iterate one epoch's batches (device-sharded), with background
        host-side prefetch replacing DataLoader worker processes."""
        orders = self._epoch_shard_orders(epoch)

        def produce():
            for step in range(self.steps_per_epoch):
                sl = slice(step * self.batch_size,
                           (step + 1) * self.batch_size)
                # Event-stream-only span (it runs in the prefetch
                # thread, concurrent with the consumer's step — the
                # goodput ledger counts only the consumer-side
                # data_wait). Assemble BEFORE yield so the span
                # doesn't stay open while the consumer trains.
                with telemetry.span("data_assemble",
                                    step_in_epoch=step):
                    batch = self._assemble_with_retry(
                        orders[:, sl], epoch=epoch, step_in_epoch=step)
                yield batch

        if self.prefetch_depth > 0:
            yield from _prefetch(produce(), self.prefetch_depth)
        else:
            yield from produce()

    def __len__(self) -> int:
        return self.steps_per_epoch


def _prefetch(it: Iterator, depth: int) -> Iterator:
    """Run ``it`` in a daemon thread, keeping ``depth`` items ready.

    The host-side analogue of DataLoader's worker+pin_memory pipelining
    (reference: src/distributed_trainer.py:206-208): batch assembly and
    H2D transfer overlap with device compute.
    """
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()
    err: list[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            q.put(_END)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            if err:
                raise err[0]
            return
        yield item
