"""Datasets: map-style, NumPy-backed, deterministic.

The reference's datasets are tiny synthetic tensors created eagerly on the
host (``MyTrainDataset``: ``size`` pairs of ``(rand(20), rand(1))``,
src/data_utils.py:7-16; the playground's ``DummyDataset``:
``(randn(10), randn(1))``, src/playground/ddp_script.py:26-36). We keep that
map-style contract — ``len(ds)`` and ``ds[i] -> dict of arrays`` — because
the DistributedSampler arithmetic is defined over it, but store columnar
NumPy so a whole index-batch gathers in one fancy-index op.
"""

from __future__ import annotations

from typing import Mapping, Protocol

import numpy as np


class Dataset(Protocol):
    """Map-style dataset: columnar access by index array."""

    def __len__(self) -> int: ...

    def batch(self, indices: np.ndarray) -> Mapping[str, np.ndarray]:
        """Gather rows for ``indices`` into a dict of stacked arrays."""
        ...


class ArrayDataset:
    """Columnar in-memory dataset over named NumPy arrays."""

    def __init__(self, **columns: np.ndarray):
        if not columns:
            raise ValueError("ArrayDataset needs at least one column")
        sizes = {k: len(v) for k, v in columns.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"column length mismatch: {sizes}")
        self.columns = dict(columns)
        self._size = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self._size

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        # Native multithreaded row gather when compiled (exact-equal to
        # NumPy fancy indexing; distributed_training_tpu/native).
        from distributed_training_tpu import native
        return {k: native.gather_rows(v, indices)
                for k, v in self.columns.items()}


class SyntheticRegressionDataset(ArrayDataset):
    """Parity with the reference's synthetic data distributions.

    ``kind="uniform"`` reproduces ``MyTrainDataset`` (rand(in_dim), rand(1);
    src/data_utils.py:10); ``kind="normal"`` reproduces the playground's
    ``DummyDataset`` (randn; src/playground/ddp_script.py:30-32) whose
    targets carry a learnable linear signal via the loss (MSE). Data is
    generated once, seeded, identical on every process — the TPU analogue
    of every rank building the same dataset then sampling its shard.
    """

    def __init__(self, size: int = 2048, in_dim: int = 20, out_dim: int = 1,
                 seed: int = 0, kind: str = "uniform"):
        rng = np.random.default_rng(seed)
        if kind == "uniform":
            x = rng.random((size, in_dim), dtype=np.float32)
            y = rng.random((size, out_dim), dtype=np.float32)
        elif kind == "normal":
            x = rng.standard_normal((size, in_dim), dtype=np.float32)
            y = rng.standard_normal((size, out_dim), dtype=np.float32)
        elif kind == "linear":
            # A solvable regression task (for convergence tests): y = xW + b
            # + noise. The reference's default task is degenerate (SURVEY.md
            # §8 B5); this kind exists so convergence is actually testable.
            w = rng.standard_normal((in_dim, out_dim), dtype=np.float32)
            b = rng.standard_normal((out_dim,), dtype=np.float32)
            x = rng.standard_normal((size, in_dim), dtype=np.float32)
            noise = 0.01 * rng.standard_normal((size, out_dim),
                                               dtype=np.float32)
            y = x @ w + b + noise
        else:
            raise ValueError(f"unknown kind: {kind}")
        super().__init__(x=x, y=y)


class SyntheticLMDataset(ArrayDataset):
    """Synthetic language-model corpus: random token sequences with a
    next-token structure (each row is ``seq_len + 1`` tokens; the model sees
    ``tokens[:-1]`` and predicts ``tokens[1:]``). Stands in for the
    OpenWebText shard of BASELINE.json config 3 in tests/benches."""

    def __init__(self, size: int = 1024, seq_len: int = 128,
                 vocab_size: int = 50257, seed: int = 0):
        # Native multithreaded token fill when compiled; the NumPy
        # fallback replays the identical SplitMix64 stream, so every
        # host materializes the same corpus even when native build
        # availability differs across hosts (the property the
        # multi-host data path relies on).
        from distributed_training_tpu import native
        tokens = native.fill_tokens(
            seed, vocab_size, size * (seq_len + 1)).reshape(
                size, seq_len + 1)
        super().__init__(tokens=tokens)
        self.seq_len = seq_len
        self.vocab_size = vocab_size


class SyntheticImageDataset(ArrayDataset):
    """Synthetic labelled images (CIFAR-10-shaped by default) for the
    ResNet config of BASELINE.json when no real data is present."""

    def __init__(self, size: int = 1024, height: int = 32, width: int = 32,
                 channels: int = 3, num_classes: int = 10, seed: int = 0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((size, height, width, channels),
                                dtype=np.float32)
        y = rng.integers(0, num_classes, (size,), dtype=np.int32)
        super().__init__(x=x, y=y)
        self.num_classes = num_classes


class SyntheticDocDataset:
    """Variable-length synthetic token DOCUMENTS (ragged, stored as one
    flat token array + offsets) — the shape real pretraining corpora
    have before packing. Row ``i`` is a doc of ``min_len..max_len``
    tokens; the streaming packer (data/stream.py) reads docs exactly
    via ``doc(i)`` and concatenates them into fixed blocks.

    ``batch`` keeps the map-style contract for probes by zero-padding
    to the corpus max length — training should consume this dataset
    through the packer, which never pads."""

    def __init__(self, size: int = 256, min_len: int = 16,
                 max_len: int = 96, vocab_size: int = 50257,
                 seed: int = 0):
        if not 0 < min_len <= max_len:
            raise ValueError(
                f"need 0 < min_len <= max_len, got {min_len}..{max_len}")
        rng = np.random.default_rng([seed, 0x0D0C])
        lengths = rng.integers(min_len, max_len + 1, size)
        self._offsets = np.concatenate(
            [[0], np.cumsum(lengths)]).astype(np.int64)
        self._tokens = rng.integers(
            0, vocab_size, int(self._offsets[-1]), dtype=np.int32)
        self._size = size
        self.vocab_size = vocab_size
        self.max_len = max_len

    def __len__(self) -> int:
        return self._size

    def doc(self, i: int) -> np.ndarray:
        return self._tokens[self._offsets[i]:self._offsets[i + 1]]

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        out = np.zeros((len(indices), self.max_len), dtype=np.int32)
        for r, i in enumerate(np.asarray(indices)):
            d = self.doc(int(i))
            out[r, :len(d)] = d
        return {"tokens": out}


class MemmapTokenDataset:
    """Token corpus over a flat binary file of token ids (np.memmap), the
    standard 'tokenized shard on shared storage' layout for real LM
    pretraining. Rows are non-overlapping windows of ``seq_len + 1``."""

    def __init__(self, path: str, seq_len: int, dtype: str = "uint16",
                 vocab_size: int = 50257):
        self._data = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._size = (len(self._data) - 1) // seq_len
        if self._size <= 0:
            raise ValueError(f"{path} too small for seq_len={seq_len}")

    def __len__(self) -> int:
        return self._size

    def batch(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        starts = indices.astype(np.int64) * self.seq_len
        offsets = np.arange(self.seq_len + 1, dtype=np.int64)
        window = starts[:, None] + offsets[None, :]
        return {"tokens": np.asarray(self._data[window], dtype=np.int32)}


class SubsetDataset:
    """Index-remapped view of a base dataset (no copy)."""

    def __init__(self, base, indices: np.ndarray):
        self._base = base
        self._indices = np.asarray(indices, dtype=np.int64)
        # Surface base attributes models/loaders key off (vocab_size,
        # seq_len, num_classes, ...).
        for attr in ("vocab_size", "seq_len", "num_classes"):
            if hasattr(base, attr):
                setattr(self, attr, getattr(base, attr))

    def __len__(self) -> int:
        return len(self._indices)

    def batch(self, indices: np.ndarray) -> Mapping[str, np.ndarray]:
        return self._base.batch(self._indices[indices])


def train_eval_split(ds, eval_fraction: float, seed: int = 0,
                     multiple_of: int = 1):
    """Deterministic disjoint (train, eval) split of a map-style
    dataset. The permutation is seed-keyed and identical on every
    process (same contract as the sampler's shuffle).

    ``multiple_of``: round the eval size UP to this multiple (callers
    pass the global batch size). With an exact multiple, the sharded
    loader never wrap-pads eval batches, so val_loss is an exact mean
    over the eval rows — padding would double-count duplicated rows
    and make val_loss depend on the pod's shard count."""
    if not 0.0 < eval_fraction < 1.0:
        raise ValueError(
            f"eval_fraction must be in (0, 1), got {eval_fraction}")
    if multiple_of < 1:
        raise ValueError(f"multiple_of must be >= 1, got {multiple_of}")
    n = len(ds)
    n_eval = max(1, int(round(n * eval_fraction)))
    n_eval = -(-n_eval // multiple_of) * multiple_of  # ceil to multiple
    if n_eval >= n:
        raise ValueError(
            f"eval_fraction={eval_fraction} (rounded to a multiple of "
            f"{multiple_of} -> {n_eval}) leaves no training data "
            f"(dataset size {n})")
    perm = np.random.default_rng(seed).permutation(n)
    return (SubsetDataset(ds, perm[n_eval:]),
            SubsetDataset(ds, perm[:n_eval]))


def build_dataset(name: str, _defaults: dict | None = None,
                  **kwargs) -> Dataset:
    """Dataset registry keyed by config ``train.dataset``.

    ``_defaults`` are soft kwargs (size/seed from TrainConfig) applied
    only when the builder accepts them and the user didn't override —
    file-backed datasets like ``memmap_tokens`` take neither.
    Explicit ``kwargs`` are passed through unfiltered so typos fail loudly.
    """
    builders = {
        "synthetic": SyntheticRegressionDataset,
        "synthetic_normal": lambda **kw: SyntheticRegressionDataset(
            kind="normal", **kw),
        "synthetic_linear": lambda **kw: SyntheticRegressionDataset(
            kind="linear", **kw),
        "synthetic_lm": SyntheticLMDataset,
        "synthetic_doc": SyntheticDocDataset,
        "synthetic_images": SyntheticImageDataset,
        "memmap_tokens": MemmapTokenDataset,
        # Byte-level LM over ANY local file: the zero-dependency real-
        # data path (subword tokenizers need downloaded vocab files;
        # bytes need nothing). vocab_size 256, uint8 storage.
        "bytes": lambda path, seq_len: MemmapTokenDataset(
            path, seq_len, dtype="uint8", vocab_size=256),
    }
    if name not in builders:
        raise ValueError(
            f"unknown dataset '{name}'; known: {sorted(builders)}")
    builder = builders[name]
    if _defaults:
        import inspect
        try:
            sig = inspect.signature(builder)
            accepted = {
                k: v for k, v in _defaults.items()
                if k in sig.parameters or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in sig.parameters.values())
            }
        except (TypeError, ValueError):  # pragma: no cover
            accepted = dict(_defaults)
        kwargs = {**accepted, **kwargs}
    return builder(**kwargs)
