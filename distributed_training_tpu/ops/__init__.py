"""ops: TPU compute kernels and their reference implementations.

The hot ops live here: attention (naive XLA reference, Pallas flash
kernel, ring-attention sequence-parallel variant). Everything is a pure
function over arrays so models stay kernel-agnostic; dispatch is by
``impl=`` argument resolved from config.
"""

from distributed_training_tpu.ops.attention import (  # noqa: F401
    dot_product_attention,
)
from distributed_training_tpu.ops.xent import (  # noqa: F401
    lm_cross_entropy,
)
