"""Pallas TPU flash attention: blockwise online-softmax, O(S) memory.

Forward + custom-VJP backward, both as Pallas kernels. Design (per the
TPU kernel playbook, /opt/skills/guides/pallas_guide.md):

- grid ``(B, H, nq, nk)``: the innermost ``nk`` dimension executes
  sequentially per core, so softmax statistics (running max ``m``,
  normalizer ``l``) and the output accumulator live in VMEM scratch and
  carry across k-blocks; the q-block output is finalized on the last
  k-step. Q/K/V blocks stream HBM→VMEM via BlockSpec pipelining (the
  compiler double-buffers automatically).
- all matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``); inputs may be bf16.
- causal masking is applied per-block; fully-masked k-blocks are skipped
  with ``pl.when`` so the causal program does ~half the FLOPs.
- backward uses the saved logsumexp and ``delta = rowsum(dO * O)``
  (computed in XLA, it fuses). Default: a FUSED single-sweep kernel
  producing dq/dk/dv together — the block's softmax (s, exp, dp) is
  computed once instead of twice and q/k/v/do stream from HBM once;
  dq accumulates in a full (S, D) f32 VMEM scratch so its
  across-k-blocks accumulation needs no dedicated grid order. When
  that scratch would not fit VMEM (very long S), falls back to the
  standard FlashAttention-2 two-kernel decomposition: dq (accumulate
  over k-blocks) and dkv (accumulate over q-blocks).

Layout contract: wrapper takes (B, S, H, D) like ops.attention, kernels
work in (B, H, S, D). GQA keeps K/V at Hkv heads end-to-end: the KV
BlockSpec index maps route q-head ``h`` to kv-head ``h // reps``, so
grouped heads are never materialized (dk/dv are group-reduced after the
kernel). Sequence lengths must divide the block size (the transformer's
seq lens are powers of two ≥ 128; others fall back to naive).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256   # legacy floor — the real default is seq-aware,
DEFAULT_BLOCK_K = 256   # see default_blocks()
NEG_INF = -1e30


def default_blocks(seq_q: int, seq_k: int,
                   head_dim: int) -> tuple[int, int]:
    """Largest tiles that divide the sequences and fit VMEM comfortably.

    MEASURED (v5e, r4 tune matrix, GPT-2 125M @ S=1024, batch 32):
    per-block overheads — causal-mask iota, online-softmax rescale,
    scratch init/finalize, and the (block, 64)-thin MXU ops — dominate
    at small tiles. 256x256 -> 512x512 -> 1024x1024 moved the full
    train step 0.274 -> 0.367 -> 0.419 MFU (+53% tok/s), while XLA's
    fused naive attention sat at 0.269; block_k mattered more than
    block_q (512x1024 beat 1024x512, 0.401 vs 0.364). VMEM budget:
    the f32 logits tile (bq x bk = 4 MiB at 1024x1024) plus q/k/v/do
    blocks and f32 scratch, double-buffered, fits the ~16 MiB/core
    VMEM at head_dim <= 128; wider heads cap at 512.
    """
    cap = 1024 if head_dim <= 128 else 512

    def pick(s: int) -> int:
        for b in (cap, 512, 256, 128):
            if b <= s and s % b == 0:
                return b
        if s <= cap:
            return s  # one whole-sequence block (also the s < 128 case)
        # No dividing tile and too long for a single block: refuse (0)
        # rather than hand Mosaic an over-VMEM logits tile — auto
        # dispatch falls back to naive, forced flash raises loudly.
        return 0

    return pick(seq_q), pick(seq_k)


def _resolve_blocks(block_q: int, block_k: int, seq_q: int, seq_k: int,
                    head_dim: int) -> tuple[int, int]:
    """Effective tiles: explicit overrides (seq-clamped) win; zeros take
    the measured seq-aware defaults."""
    dq, dk = default_blocks(seq_q, seq_k, head_dim)
    return (min(block_q, seq_q) if block_q else dq,
            min(block_k, seq_k) if block_k else dk)

# jax < 0.4.38 spells it TPUCompilerParams (same fields); resolve the
# modern name first so this module imports on both vintages.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# Every kernel here runs a (B, H, outer, inner) grid where only the
# innermost dim carries accumulation order (fwd/dq: k-blocks; dkv:
# q-blocks) — declaring the rest parallel lets Mosaic pipeline them.
_DIM_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel",
                         "arbitrary"))


def _block_needed(causal: bool, q_start, k_start, block_q: int,
                  block_k: int = 0, window: int = 0):
    """False for k-blocks with no live (query, key) pair: entirely
    above the causal diagonal, or — with a sliding ``window`` (query i
    attends keys in [i − window + 1, i]) — entirely below every
    query's window start. Skipped blocks cost zero FLOPs, so windowed
    attention is O(S·window), not O(S²)."""
    needed = jnp.logical_or(not causal, k_start <= q_start + block_q - 1)
    if window > 0:
        needed = jnp.logical_and(
            needed,
            k_start + block_k - 1 >= q_start - window + 1)
    return needed


def _apply_causal_mask(s, q_start, k_start, block_q: int, block_k: int,
                       window: int = 0):
    rows = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    live = cols <= rows
    if window > 0:
        live = jnp.logical_and(live, cols >= rows - (window - 1))
    return jnp.where(live, s, NEG_INF)


def _platform_is_tpu() -> bool:
    """True when tracing targets a TPU backend.

    DTT_ASSUME_TPU=1 overrides the attached-device check (read
    dynamically, not at import: it exists for DEVICE-LESS topology AOT
    compiles — runtime.topology_runtime — where jax.devices() reports
    the host CPU even though the program is being compiled by the real
    TPU compiler; without the override those audits trace the naive
    path and 0 Pallas kernels reach the compiled HLO). Never set it in
    a process that will EXECUTE the program on CPU: the kernels would
    run in compiled (non-interpret) mode on a backend without Mosaic."""
    if os.environ.get("DTT_ASSUME_TPU", "0") not in ("", "0"):
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def supported(q: jax.Array, k: jax.Array, v: jax.Array,
              block_q: int = 0, block_k: int = 0,
              layout: str = "bshd") -> bool:
    """Should auto-dispatch route here? (Else: naive fallback.)

    Conservative by design: off-TPU the interpreter would be orders of
    magnitude slower than XLA's fused naive path, and the kernel's
    causal mask assumes Sq == Sk (no bottom-right offset).
    ``block_q``/``block_k`` are the caller's tile overrides (0 → kernel
    defaults) — divisibility is checked against the EFFECTIVE tiles so
    a non-dividing override falls back instead of crashing the trace.
    ``layout``: where the sequence/head axes live ("bshd" or "bhsd").
    """
    del v
    s_ax, h_ax = (2, 1) if layout == "bhsd" else (1, 2)
    if not _platform_is_tpu():
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if q.shape[s_ax] != k.shape[s_ax]:
        return False
    if q.shape[s_ax] < 128:
        return False
    bq, bk = _resolve_blocks(block_q, block_k, q.shape[s_ax],
                             k.shape[s_ax], q.shape[3])
    if not bq or not bk or q.shape[s_ax] % bq or k.shape[s_ax] % bk:
        return False
    if q.shape[3] > 256:
        return False
    if q.shape[h_ax] % k.shape[h_ax]:
        return False
    return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, block_q, block_k,
                causal, window=0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Causal: skip blocks entirely above the diagonal (and, with a
    # sliding window, entirely below it).
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]  # (block_q, d)
        k = k_ref[0, 0]  # (block_k, d)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)

        m_prev = m_ref[:]                          # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)            # (bq, 1)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        lsum = l_ref[:]
        l_safe = jnp.where(lsum == 0.0, 1.0, lsum)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:] + jnp.log(l_safe)  # (bq, 1)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, out_dtype=None,
               window=0):
    """q: (B, H, S, D); k/v: (B, Hkv, Sk, D) with Hkv dividing H — GQA is
    expressed in the KV BlockSpec index maps (h → h // reps), so grouped
    KV heads are never materialized at H resolution in HBM.
    ``out_dtype``: output dtype (default q.dtype); ring callers pass
    f32 so per-block partials aren't rounded before the merge."""
    out_dtype = out_dtype or q.dtype
    B, H, S, D = q.shape
    Sk = k.shape[2]
    reps = H // k.shape[1]
    scale = D ** -0.5
    nq, nk = S // block_q, Sk // block_k
    grid = (B, H, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            # trailing dim of 1: satisfies the (8, 128)-or-full tiling
            # rule for the per-row logsumexp residual
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), out_dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=not _platform_is_tpu(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, block_q, block_k, causal,
                   window=0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # MXU operands stay in the INPUT dtype (bf16 for the model
        # path); only accumulation is f32. Upcasting `do` here made
        # the dp matmul run f32xf32 — fractional MXU rate for zero
        # numerics benefit (the f32 work was discarded into a bf16-
        # rounded ds anyway). FlashAttention-2 semantics: bf16
        # operands, f32 accumulate, f32 softmax statistics.
        do = do_ref[0, 0].astype(v.dtype)
        lse = lse_ref[0, 0]                       # (bq, 1)
        delta = delta_ref[0, 0]                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)
        p = jnp.exp(s - lse)                       # (bq, bk) f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, block_q,
                    block_k, causal, window=0):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # Same operand-dtype discipline as the dq kernel (see note
        # there): p is rounded to the input dtype for the dv matmul
        # exactly as the forward rounds p for the pv matmul.
        do = do_ref[0, 0].astype(v.dtype)
        lse = lse_ref[0, 0]                       # (bq, 1)
        delta = delta_ref[0, 0]                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)
        p = jnp.exp(s - lse)                       # (bq, bk) f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc,
                      *, scale, block_q, block_k, causal, window=0):
    """Single-pass backward: dq, dk, dv in ONE (ki, qi) sweep.

    The two-kernel FlashAttention-2 decomposition recomputes the
    block's softmax twice — s and dp matmuls plus the exp run in BOTH
    the dq and dkv kernels (7 matmuls + 2 exps per live block pair).
    Fusing shares them (5 matmuls + 1 exp) and streams q/k/v/do from
    HBM once instead of twice. The trick that makes single-pass
    possible on TPU's sequential grid: dq accumulates in a FULL
    (S, D) f32 VMEM scratch (dk/dv keep per-k-block scratch as
    before), written out on the final grid step — so dq's
    across-k-blocks accumulation no longer needs its own grid order.
    Callers guard VMEM residency (scratch + dq output block); see
    _flash_bwd.
    """
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nk = pl.num_programs(2)
    nq = pl.num_programs(3)

    @pl.when(jnp.logical_and(ki == 0, qi == 0))
    def _init_dq():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # Operand-dtype discipline identical to the split kernels:
        # bf16 MXU operands, f32 accumulation, f32 softmax statistics.
        do = do_ref[0, 0].astype(v.dtype)
        lse = lse_ref[0, 0]                       # (bq, 1)
        delta = delta_ref[0, 0]                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)
        p = jnp.exp(s - lse)                       # (bq, bk) f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dq_acc[pl.dslice(q_start, block_q), :] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, d)

    @pl.when(qi == nq - 1)
    def _finalize_dkv():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ki == nk - 1, qi == nq - 1))
    def _finalize_dq():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


# VMEM budget for the fused backward's TOTAL estimated residency.
# An earlier gate budgeted only the whole-sequence dq scratch + dq
# output block (6 MiB) and ignored everything else resident with it —
# the f32 (block_q, block_k) softmax temporaries, dk/dv scratch, and
# the double-buffered q/k/v/do tiles — so shapes like S=8192, D=128
# passed the gate and then blew the ~16 MiB/core VMEM in Mosaic
# (ADVICE r4, medium). The estimate is conservative-but-calibrated:
# the chip-proven split dq kernel runs the same (block_q, block_k)
# temporaries at 1024x1024 tiles, which bounds how many Mosaic keeps
# live simultaneously (~2 f32 copies; s/p and dp/ds alias).
_FUSED_BWD_VMEM_LIMIT_BYTES = 14 * 1024 * 1024


def _fused_bwd_vmem_estimate(S, D, block_q, block_k, in_itemsize,
                             g_itemsize) -> int:
    """Estimated peak VMEM residency (bytes) of _bwd_fused_kernel."""
    dq_resident = S * D * (4 + g_itemsize)       # f32 scratch + out blk
    softmax_tmp = 2 * block_q * block_k * 4      # live f32 (bq, bk)
    dkv_scratch = 2 * block_k * D * 4
    dkv_out = 2 * block_k * D * g_itemsize
    io_tiles = 2 * 2 * (block_q + block_k) * D * in_itemsize  # dbl-buf
    return dq_resident + softmax_tmp + dkv_scratch + dkv_out + io_tiles


def _fused_bwd_fits(S, D, block_q, block_k, in_dtype, grads_dtype=None):
    """Gate for the fused single-sweep backward; callers fall back to
    the chip-proven two-kernel split path when this is False."""
    g = jnp.dtype(grads_dtype or in_dtype).itemsize
    return _fused_bwd_vmem_estimate(
        S, D, block_q, block_k, jnp.dtype(in_dtype).itemsize,
        g) <= _FUSED_BWD_VMEM_LIMIT_BYTES


# DTT_FLASH_SPLIT_BWD=1 forces the two-kernel path — the chip session
# A/Bs the fused kernel against it on real hardware
# (benchmarks/chip_session.sh) before the fused default is trusted.
# Read ONCE at import: the jit cache key does not include env vars, so
# a mid-process toggle after a shape has compiled would silently reuse
# the previously chosen kernel and invalidate an in-process A/B
# (ADVICE r4). The knob is process-start-only by construction.
_FORCE_SPLIT_BWD = os.environ.get("DTT_FLASH_SPLIT_BWD", "0") not in (
    "", "0")


def _flash_bwd_fused(q, k, v, lse, do, delta, *, causal, block_q,
                     block_k, window=0, grads_dtype=None):
    """Fused single-sweep backward (see _bwd_fused_kernel)."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    reps = H // k.shape[1]
    scale = D ** -0.5
    nq, nk = S // block_q, Sk // block_k
    gdt = grads_dtype
    qi_spec = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, h, ki, qi: (b, h, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, ki, qi: (b, h // reps, ki, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, ki, qi: (b, h, qi, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale,
                          block_q=block_q, block_k=block_k,
                          causal=causal, window=window),
        grid=(B, H, nk, nq),
        in_specs=[qi_spec, kv_spec, kv_spec, qi_spec, row_spec,
                  row_spec],
        out_specs=[
            # dq: one whole-(S, D) block per (b, h), resident across
            # the entire sequential (ki, qi) sweep, stored once on the
            # last step from the f32 scratch.
            pl.BlockSpec((1, 1, S, D), lambda b, h, ki, qi: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), gdt or q.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((S, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        # Both trailing dims carry accumulation order here.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=not _platform_is_tpu(),
    )(q, k, v, do, lse, delta)
    if reps > 1:
        dk = dk.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
        dv = dv.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
    return dq, dk, dv


def _flash_bwd(q, k, v, out, lse, do, *, causal, block_q, block_k,
               window=0,
               delta=None, grads_dtype=None):
    """``out`` is consumed only to derive ``delta``; callers that
    precompute delta (it is loop-invariant in the ring) pass
    ``out=None`` and skip that read entirely. ``grads_dtype`` overrides
    the dq/dk/dv dtype (default: match the inputs); ring callers pass
    f32 so per-block gradient partials aren't rounded before their
    cross-block accumulation."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    reps = H // k.shape[1]
    scale = D ** -0.5
    nq, nk = S // block_q, Sk // block_k
    if delta is None:
        delta = jnp.sum(
            do.astype(jnp.float32) * out.astype(jnp.float32),
            axis=-1, keepdims=True)  # (B, H, S, 1) — fuses in XLA

    if (not _FORCE_SPLIT_BWD
            and _fused_bwd_fits(S, D, block_q, block_k, q.dtype,
                                grads_dtype)):
        return _flash_bwd_fused(q, k, v, lse, do, delta, causal=causal,
                                block_q=block_q, block_k=block_k,
                                window=window, grads_dtype=grads_dtype)

    gdt = grads_dtype
    interp = not _platform_is_tpu()
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal,
                          window=window),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), gdt or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_DIM_SEMANTICS,
        interpret=interp,
    )(q, k, v, do, lse, delta)

    # dk/dv are computed per q-head (grid over H) and group-reduced to
    # Hkv afterwards; KV reads stay at Hkv resolution via the index map.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal,
                          window=window),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=interp,
    )(q, k, v, do, lse, delta)
    if reps > 1:
        dk = dk.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
        dv = dv.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom VJP over BHSD internals, BSHD at the boundary)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd(q, k, v, causal, block_q, block_k, window=0):
    out, _ = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, window=window)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k, window=0):
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, window=window)
    # Checkpoint-name the residuals the backward consumes: under a
    # save_only_these_names remat policy, un-named residuals are
    # discarded and the whole forward kernel re-runs in the backward
    # (MEASURED r4, batch-32 trace: a 31.8 ms/step rematted pallas_call
    # — the policies' allow-lists carry these names so saving the
    # kernel output actually prevents the recompute it was meant to
    # prevent). The name is applied to the PRIMAL and that same value
    # is used as the residual: naming a residual-only copy would leave
    # the primal un-saved, and any downstream consumer being rematted
    # (the BSHD transpose feeding the output projection's wgrad) would
    # re-launch the kernel anyway. q/k/v residuals stay un-named on
    # purpose: their BSHD twins are already saved by the model's
    # q_rope/k_rope/v_proj tags, so their recompute is three cheap
    # transposes, not a kernel launch.
    name = jax.ad_checkpoint.checkpoint_name
    out = name(out, "flash_out")
    return out, (q, k, v, out, name(lse, "flash_lse"))


def _flash_bhsd_bwd(causal, block_q, block_k, window, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal=causal,
                            block_q=block_q, block_k=block_k,
                            window=window)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: int = 0,
                    block_k: int = 0,
                    window: int = 0,
                    layout: str = "bshd") -> jax.Array:
    """Flash attention over (B, S, H, D) inputs (GQA allowed).

    ``block_q``/``block_k`` = 0 take the measured seq-aware defaults
    (``default_blocks``); explicit values override, seq-clamped.
    ``window`` > 0 = sliding-window (Mistral-style) attention: query i
    attends keys in [i − window + 1, i]. Requires ``causal``; k-blocks
    outside the band are skipped, so cost is O(S·window).
    ``layout="bhsd"``: inputs/output already in the kernels' native
    (B, H, S, D) — skips the wrapper transposes entirely (the model's
    fast path emits this layout straight from its qkv einsums)."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if layout not in ("bshd", "bhsd"):
        raise ValueError(f"unknown layout '{layout}'")
    native = layout == "bhsd"
    s_ax, h_ax = (2, 1) if native else (1, 2)
    S, D = q.shape[s_ax], q.shape[3]
    H, Hkv = q.shape[h_ax], k.shape[h_ax]
    Sk = k.shape[s_ax]
    if S != Sk and causal:
        raise ValueError(
            f"flash kernel's causal mask requires Sq == Sk, got "
            f"{S} vs {Sk}; use impl='naive'")
    if H % Hkv:
        raise ValueError(
            f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    bq, bk = _resolve_blocks(block_q, block_k, S, Sk, D)
    if not bq or not bk or S % bq or Sk % bk:
        raise ValueError(
            f"sequence lengths ({S}, {Sk}) must be divisible by "
            f"block sizes ({bq}, {bk}); pad or use impl='naive'")
    if native:
        return _flash_bhsd(q, k, v, causal, bq, bk, window)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash_bhsd(qt, kt, vt, causal, bq, bk, window)
    return jnp.transpose(out, (0, 2, 1, 3))
