"""Pallas TPU flash attention (blockwise-softmax, O(S) memory).

Kernel lands in the flash-attention milestone; until then ``supported``
returns False and dispatch in ops/attention.py falls back to the naive
XLA implementation, which is numerically identical.
"""

from __future__ import annotations

import jax


def supported(q: jax.Array, k: jax.Array, v: jax.Array) -> bool:
    return False


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    raise NotImplementedError(
        "Pallas flash attention kernel not yet built; use impl='naive'")
