"""Pallas TPU flash attention: blockwise online-softmax, O(S) memory.

Forward + custom-VJP backward, both as Pallas kernels. Design (per the
TPU kernel playbook, /opt/skills/guides/pallas_guide.md):

- grid ``(B, H, nq, nk)``: the innermost ``nk`` dimension executes
  sequentially per core, so softmax statistics (running max ``m``,
  normalizer ``l``) and the output accumulator live in VMEM scratch and
  carry across k-blocks; the q-block output is finalized on the last
  k-step. Q/K/V blocks stream HBM→VMEM via BlockSpec pipelining (the
  compiler double-buffers automatically).
- all matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``); inputs may be bf16.
- causal masking is applied per-block; fully-masked k-blocks are skipped
  with ``pl.when`` so the causal program does ~half the FLOPs.
- backward uses the saved logsumexp and ``delta = rowsum(dO * O)``
  (computed in XLA, it fuses) and two kernels: dq (accumulate over
  k-blocks) and dkv (accumulate over q-blocks) — the standard
  FlashAttention-2 decomposition.

Layout contract: wrapper takes (B, S, H, D) like ops.attention, kernels
work in (B, H, S, D). GQA keeps K/V at Hkv heads end-to-end: the KV
BlockSpec index maps route q-head ``h`` to kv-head ``h // reps``, so
grouped heads are never materialized (dk/dv are group-reduced after the
kernel). Sequence lengths must divide the block size (the transformer's
seq lens are powers of two ≥ 128; others fall back to naive).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30

# Every kernel here runs a (B, H, outer, inner) grid where only the
# innermost dim carries accumulation order (fwd/dq: k-blocks; dkv:
# q-blocks) — declaring the rest parallel lets Mosaic pipeline them.
_DIM_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel",
                         "arbitrary"))


def _block_needed(causal: bool, q_start, k_start, block_q: int,
                  block_k: int = 0, window: int = 0):
    """False for k-blocks with no live (query, key) pair: entirely
    above the causal diagonal, or — with a sliding ``window`` (query i
    attends keys in [i − window + 1, i]) — entirely below every
    query's window start. Skipped blocks cost zero FLOPs, so windowed
    attention is O(S·window), not O(S²)."""
    needed = jnp.logical_or(not causal, k_start <= q_start + block_q - 1)
    if window > 0:
        needed = jnp.logical_and(
            needed,
            k_start + block_k - 1 >= q_start - window + 1)
    return needed


def _apply_causal_mask(s, q_start, k_start, block_q: int, block_k: int,
                       window: int = 0):
    rows = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    live = cols <= rows
    if window > 0:
        live = jnp.logical_and(live, cols >= rows - (window - 1))
    return jnp.where(live, s, NEG_INF)


def _platform_is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def supported(q: jax.Array, k: jax.Array, v: jax.Array,
              block_q: int = 0, block_k: int = 0) -> bool:
    """Should auto-dispatch route here? (Else: naive fallback.)

    Conservative by design: off-TPU the interpreter would be orders of
    magnitude slower than XLA's fused naive path, and the kernel's
    causal mask assumes Sq == Sk (no bottom-right offset).
    ``block_q``/``block_k`` are the caller's tile overrides (0 → kernel
    defaults) — divisibility is checked against the EFFECTIVE tiles so
    a non-dividing override falls back instead of crashing the trace.
    """
    del v
    if not _platform_is_tpu():
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if q.shape[1] != k.shape[1]:
        return False
    if q.shape[1] < 128:
        return False
    bq = min(block_q or DEFAULT_BLOCK_Q, q.shape[1])
    bk = min(block_k or DEFAULT_BLOCK_K, k.shape[1])
    if q.shape[1] % bq or k.shape[1] % bk:
        return False
    if q.shape[3] > 256:
        return False
    if q.shape[2] % k.shape[2]:
        return False
    return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, block_q, block_k,
                causal, window=0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Causal: skip blocks entirely above the diagonal (and, with a
    # sliding window, entirely below it).
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]  # (block_q, d)
        k = k_ref[0, 0]  # (block_k, d)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)

        m_prev = m_ref[:]                          # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)            # (bq, 1)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        lsum = l_ref[:]
        l_safe = jnp.where(lsum == 0.0, 1.0, lsum)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:] + jnp.log(l_safe)  # (bq, 1)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, out_dtype=None,
               window=0):
    """q: (B, H, S, D); k/v: (B, Hkv, Sk, D) with Hkv dividing H — GQA is
    expressed in the KV BlockSpec index maps (h → h // reps), so grouped
    KV heads are never materialized at H resolution in HBM.
    ``out_dtype``: output dtype (default q.dtype); ring callers pass
    f32 so per-block partials aren't rounded before the merge."""
    out_dtype = out_dtype or q.dtype
    B, H, S, D = q.shape
    Sk = k.shape[2]
    reps = H // k.shape[1]
    scale = D ** -0.5
    nq, nk = S // block_q, Sk // block_k
    grid = (B, H, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            # trailing dim of 1: satisfies the (8, 128)-or-full tiling
            # rule for the per-row logsumexp residual
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), out_dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=not _platform_is_tpu(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, block_q, block_k, causal,
                   window=0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # MXU operands stay in the INPUT dtype (bf16 for the model
        # path); only accumulation is f32. Upcasting `do` here made
        # the dp matmul run f32xf32 — fractional MXU rate for zero
        # numerics benefit (the f32 work was discarded into a bf16-
        # rounded ds anyway). FlashAttention-2 semantics: bf16
        # operands, f32 accumulate, f32 softmax statistics.
        do = do_ref[0, 0].astype(v.dtype)
        lse = lse_ref[0, 0]                       # (bq, 1)
        delta = delta_ref[0, 0]                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)
        p = jnp.exp(s - lse)                       # (bq, bk) f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, block_q,
                    block_k, causal, window=0):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = _block_needed(causal, q_start, k_start, block_q,
                           block_k, window)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # Same operand-dtype discipline as the dq kernel (see note
        # there): p is rounded to the input dtype for the dv matmul
        # exactly as the forward rounds p for the pv matmul.
        do = do_ref[0, 0].astype(v.dtype)
        lse = lse_ref[0, 0]                       # (bq, 1)
        delta = delta_ref[0, 0]                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, q_start, k_start, block_q,
                                   block_k, window)
        p = jnp.exp(s - lse)                       # (bq, bk) f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale              # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, *, causal, block_q, block_k,
               window=0,
               delta=None, grads_dtype=None):
    """``out`` is consumed only to derive ``delta``; callers that
    precompute delta (it is loop-invariant in the ring) pass
    ``out=None`` and skip that read entirely. ``grads_dtype`` overrides
    the dq/dk/dv dtype (default: match the inputs); ring callers pass
    f32 so per-block gradient partials aren't rounded before their
    cross-block accumulation."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    reps = H // k.shape[1]
    scale = D ** -0.5
    nq, nk = S // block_q, Sk // block_k
    if delta is None:
        delta = jnp.sum(
            do.astype(jnp.float32) * out.astype(jnp.float32),
            axis=-1, keepdims=True)  # (B, H, S, 1) — fuses in XLA

    gdt = grads_dtype
    interp = not _platform_is_tpu()
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal,
                          window=window),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), gdt or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_DIM_SEMANTICS,
        interpret=interp,
    )(q, k, v, do, lse, delta)

    # dk/dv are computed per q-head (grid over H) and group-reduced to
    # Hkv afterwards; KV reads stay at Hkv resolution via the index map.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal,
                          window=window),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h // reps, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, ki, qi: (b, h, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), gdt or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=interp,
    )(q, k, v, do, lse, delta)
    if reps > 1:
        dk = dk.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
        dv = dv.reshape(B, H // reps, reps, Sk, D).sum(axis=2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom VJP over BHSD internals, BSHD at the boundary)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd(q, k, v, causal, block_q, block_k, window=0):
    out, _ = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, window=window)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k, window=0):
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, window=window)
    return out, (q, k, v, out, lse)


def _flash_bhsd_bwd(causal, block_q, block_k, window, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal=causal,
                            block_q=block_q, block_k=block_k,
                            window=window)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    window: int = 0) -> jax.Array:
    """Flash attention over (B, S, H, D) inputs (GQA allowed).

    ``window`` > 0 = sliding-window (Mistral-style) attention: query i
    attends keys in [i − window + 1, i]. Requires ``causal``; k-blocks
    outside the band are skipped, so cost is O(S·window)."""
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if S != k.shape[1] and causal:
        raise ValueError(
            f"flash kernel's causal mask requires Sq == Sk, got "
            f"{S} vs {k.shape[1]}; use impl='naive'")
    if H % Hkv:
        raise ValueError(
            f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    bq = min(block_q, S)
    bk = min(block_k, k.shape[1])
    if S % bq or k.shape[1] % bk:
        raise ValueError(
            f"sequence lengths ({S}, {k.shape[1]}) must be divisible by "
            f"block sizes ({bq}, {bk}); pad or use impl='naive'")
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash_bhsd(qt, kt, vt, causal, bq, bk, window)
    return jnp.transpose(out, (0, 2, 1, 3))
