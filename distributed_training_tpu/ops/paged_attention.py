"""Paged attention: decode/prefill attention over a paged KV pool.

The serving KV cache (serving/kv_cache.py) stores keys/values in
fixed-size PAGES drawn from a preallocated pool — virtual memory for
KV, so concurrent sequences of wildly different lengths share one HBM
reservation with no per-sequence max_len buffers and no copying on
join/evict. This module is the attention math over that layout:

- pool layout (per layer): ``k_pages``/``v_pages`` of shape
  ``(n_kv_heads, num_pages, page_size, head_dim)`` — kv-head-major,
  the canonical layout of the TPU Pallas paged-attention kernel
  (``jax.experimental.pallas.ops.tpu.paged_attention``), so the
  kernel path needs zero relayout;
- per-sequence ``page_indices`` row: logical page ``j`` of the
  sequence lives in physical page ``page_indices[j]``; logical
  position ``p`` is slot ``p % page_size`` of logical page
  ``p // page_size``.

Two entrypoints:

- ``paged_attention`` — single-token decode: one query per sequence
  against its pages. Dispatches to the TPU Pallas kernel when
  ``kernel_supported`` (one async DMA per non-contiguous page,
  double-buffered — see the Pallas guide's paged-attention walk-
  through); everywhere else (CPU meshes, odd shapes) the XLA
  reference path gathers pages dense and masks. Exact same numerics
  contract as ops/attention.py: fp32 logits/softmax, output in
  q.dtype, GQA via hkv-major grouping.
- ``paged_attention_chunk`` — multi-query (prefill-chunk) form: ``S``
  queries per sequence, each masked to pages at logical positions
  ``<= its own position``. Used by the engine's chunked prefill for
  chunks after the first (the first chunk has no prefix and runs the
  ordinary causal path, flash-eligible, via ops.attention).

Gather-based reference is O(max_pages * page_size) per query
regardless of true length — correct everywhere, and on CPU test
meshes (tiny pools) the gather is cheap. The kernel path reads only
the pages a sequence actually owns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kernel_supported(q: jax.Array, k_pages: jax.Array,
                     page_size: int | None = None) -> bool:
    """Should single-token decode dispatch to the TPU Pallas kernel?

    Conservative, mirroring ops/flash_attention.supported(): TPU
    platform only (elsewhere the interpreter is orders of magnitude
    slower than XLA's gather), MXU-friendly head_dim, and a page size
    the kernel's DMA descriptor tiles evenly."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except RuntimeError:  # pragma: no cover - backend init failure
        return False
    head_dim = q.shape[-1]
    ps = page_size if page_size is not None else k_pages.shape[2]
    if head_dim % 128:
        return False
    if ps % 16:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    return True


def _gather_pages(pages: jax.Array, page_indices: jax.Array
                  ) -> jax.Array:
    """(Hkv, N, ps, hd) pool + (B, P) tables → (B, P*ps, Hkv, hd)
    dense per-sequence KV, logical order. Slot ``s`` of the result is
    logical position ``s`` of the sequence."""
    Hkv, _N, ps, hd = pages.shape
    B, P = page_indices.shape
    g = pages[:, page_indices]              # (Hkv, B, P, ps, hd)
    return g.transpose(1, 2, 3, 0, 4).reshape(B, P * ps, Hkv, hd)


def _masked_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      visible: jax.Array) -> jax.Array:
    """GQA attention with an explicit visibility mask.

    q (B, S, H, hd); k/v (B, Sk, Hkv, hd); visible (B, S, Sk) bool.
    fp32 logits/softmax (ops/attention.py numerics contract), output
    in q.dtype. Rows with zero visible keys (inactive batch slots)
    produce zeros, not NaN — the engine masks their outputs anyway,
    but NaN would poison debugging."""
    B, S, H, hd = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads "
                         f"{Hkv}")
    group = H // Hkv
    qg = q.reshape(B, S, Hkv, group, hd)
    logits = jnp.einsum("bshgd,bkhd->bhgsk", qg, k,
                        preferred_element_type=jnp.float32)
    logits = logits * (hd ** -0.5)
    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(visible[:, None, None], logits, neg)
    # Guard the all-masked row: subtract a rowwise-safe max and zero
    # the weights where nothing is visible.
    probs = jax.nn.softmax(logits, axis=-1)
    any_visible = jnp.any(visible, axis=-1)          # (B, S)
    probs = jnp.where(any_visible[:, None, None, :, None], probs, 0.0)
    out = jnp.einsum("bhgsk,bkhd->bshgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, S, H, hd).astype(q.dtype)


def paged_attention_chunk(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array,
                          page_indices: jax.Array,
                          q_positions: jax.Array) -> jax.Array:
    """Multi-query paged attention (prefill chunks, reference path).

    q (B, S, H, hd); pools (Hkv, N, ps, hd); page_indices (B, P);
    q_positions (B, S) int32 — each query's ABSOLUTE position. Query
    (b, s) attends logical positions ``<= q_positions[b, s]`` of
    sequence b (the chunk's own KV must already be written to the
    pool). Negative q_positions mark padding queries (zero output).
    """
    kd = _gather_pages(k_pages, page_indices)
    vd = _gather_pages(v_pages, page_indices)
    Sk = kd.shape[1]
    slot = jnp.arange(Sk, dtype=jnp.int32)
    visible = (slot[None, None, :] <= q_positions[:, :, None]) \
        & (q_positions[:, :, None] >= 0)
    return _masked_attention(q, kd, vd, visible)


def paged_attention(q: jax.Array, k_pages: jax.Array,
                    v_pages: jax.Array, lengths: jax.Array,
                    page_indices: jax.Array,
                    impl: str = "auto") -> jax.Array:
    """Single-token decode attention against the paged pool.

    q (B, H, hd) — the current token's query per sequence; pools
    (Hkv, N, ps, hd); lengths (B,) int32 — VALID kv entries per
    sequence, current token's k/v included (attends logical positions
    ``[0, lengths)``; 0 = inactive slot, zero output); page_indices
    (B, P). ``impl``: "auto" (TPU kernel when supported, else
    reference), "kernel", "ref".
    """
    if impl not in ("auto", "kernel", "ref"):
        raise ValueError(f"unknown paged-attention impl '{impl}'")
    use_kernel = (impl == "kernel"
                  or (impl == "auto"
                      and kernel_supported(q, k_pages)))
    if use_kernel:  # pragma: no cover - needs a TPU
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as tpu_paged_attention,
        )
        # Kernel layout: q (B, H, hd), pools (Hkv, N, ps, hd),
        # lengths (B,), page_indices (B, P) — ours verbatim.
        return tpu_paged_attention(
            q, k_pages, v_pages, lengths, page_indices,
            pages_per_compute_block=min(4, page_indices.shape[1]))
    out = paged_attention_chunk(
        q[:, None], k_pages, v_pages, page_indices,
        (lengths - 1)[:, None].astype(jnp.int32))
    return out[:, 0]
