"""Memory-efficient LM softmax cross-entropy (fused, chunked head).

The naive loss head materializes ``(B, S, V)`` fp32 logits *and* their
log-softmax for the backward pass — at GPT-2 vocab (50304) that is the
single largest buffer in the whole train step (3.3 GB at B=16, S=1024)
and caps the batch size far below what the rest of the model needs to
saturate the MXU.

This op computes per-token ``nll = logsumexp(x @ head) - (x @ head)[t]``
in row chunks under ``lax.scan`` and registers a custom VJP that
*recomputes* each chunk's logits in the backward pass instead of saving
them:

- forward residuals: ``x`` (bf16, B·S·D), ``head``, ``targets`` and the
  per-token ``lse`` (fp32, B·S) — no (N, V) buffer survives the scan;
- backward: per chunk, ``dlogits = (softmax - onehot) * dnll`` feeds the
  two head matmuls (dx, dhead) directly, fp32 accumulation on the MXU;
- extra cost is one logits recompute (+2·B·S·D·V FLOPs, ~3% of a 125M
  step) traded for gigabytes of HBM — the classic TPU trade.

No reference counterpart (its models are Linear stubs and its loss is
the degenerate ``F.cross_entropy`` of src/distributed_trainer.py:163;
SURVEY.md §8 B5) — this exists to hit the BASELINE.json MFU target.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

DEFAULT_CHUNK_ROWS = 2048


def _pad_rows(n: int, chunk: int) -> int:
    return (-n) % chunk


def _chunked(x2: jax.Array, t1: jax.Array, chunk: int):
    """(N, D) rows + (N,) targets → (C, chunk, D) / (C, chunk), padding
    with target −1 (masked out downstream)."""
    n = x2.shape[0]
    pad = _pad_rows(n, chunk)
    if pad:
        x2 = jnp.concatenate(
            [x2, jnp.zeros((pad, x2.shape[1]), x2.dtype)], axis=0)
        t1 = jnp.concatenate(
            [t1, jnp.full((pad,), -1, t1.dtype)], axis=0)
    c = x2.shape[0] // chunk
    return x2.reshape(c, chunk, -1), t1.reshape(c, chunk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lm_xent_rows(x2, head, t1, chunk):
    nll, _ = _fwd_scan(x2, head, t1, chunk)
    return nll


def _fwd_scan(x2, head, t1, chunk):
    n = x2.shape[0]
    xc, tc = _chunked(x2, t1, chunk)

    def body(_, inp):
        xb, tb = inp                        # (chunk, D), (chunk,)
        logits = jax.lax.dot_general(
            xb, head, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (chunk, V) fp32
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]),
                                  axis=-1))
        tgt = jnp.take_along_axis(
            logits, jnp.maximum(tb, 0)[:, None], axis=-1)[:, 0]
        nll = jnp.where(tb >= 0, lse - tgt, 0.0)
        return 0, (nll, lse)

    _, (nll, lse) = jax.lax.scan(body, 0, (xc, tc))
    return nll.reshape(-1)[:n], lse.reshape(-1)


def _lm_xent_fwd(x2, head, t1, chunk):
    nll, lse = _fwd_scan(x2, head, t1, chunk)
    return nll, (x2, head, t1, lse)


def _lm_xent_bwd(chunk, res, dnll):
    x2, head, t1, lse = res
    n = x2.shape[0]
    xc, tc = _chunked(x2, t1, chunk)
    pad = _pad_rows(n, chunk)
    dnll_p = (jnp.concatenate([dnll, jnp.zeros((pad,), dnll.dtype)])
              if pad else dnll)
    dc = dnll_p.reshape(-1, chunk)
    lc = lse.reshape(-1, chunk)

    def body(dhead_acc, inp):
        xb, tb, db, lb = inp
        logits = jax.lax.dot_general(
            xb, head, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # recomputed
        p = jnp.exp(logits - lb[:, None])            # softmax, fp32
        valid = (tb >= 0)
        onehot = jax.nn.one_hot(jnp.maximum(tb, 0), head.shape[1],
                                dtype=jnp.float32)
        g = jnp.where(valid, db, 0.0).astype(jnp.float32)
        dlogits = ((p - onehot) * g[:, None]).astype(x2.dtype)
        dxb = jax.lax.dot_general(
            dlogits, head, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x2.dtype)
        dhead_acc = dhead_acc + jax.lax.dot_general(
            xb, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dhead_acc, dxb

    dhead, dx = jax.lax.scan(
        body, jnp.zeros(head.shape, jnp.float32), (xc, tc, dc, lc))
    dx = dx.reshape(-1, x2.shape[1])[:n]
    return dx, dhead.astype(head.dtype), None


_lm_xent_rows.defvjp(_lm_xent_fwd, _lm_xent_bwd)


def lm_cross_entropy(x: jax.Array, head: jax.Array, targets: jax.Array,
                     chunk_rows: int = DEFAULT_CHUNK_ROWS) -> jax.Array:
    """Per-token LM loss without an (N, V) residual.

    Args:
      x: final hidden states ``(B, S, D)`` (any float dtype; matmuls
        accumulate fp32 on the MXU).
      head: unembedding ``(D, V)``.
      targets: int token ids ``(B, S)``; negative ids are masked (their
        nll and gradient contribution are exactly zero).
      chunk_rows: rows per scan step — the only (rows, V) fp32 buffer
        ever alive.

    Returns per-token nll ``(B, S)`` fp32.
    """
    b, s, d = x.shape
    nll = _lm_xent_rows(x.reshape(b * s, d), head,
                        targets.reshape(b * s), chunk_rows)
    return nll.reshape(b, s)
