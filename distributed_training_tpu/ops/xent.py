"""Memory-efficient LM softmax cross-entropy (fused, chunked head).

The naive loss head materializes ``(B, S, V)`` fp32 logits *and* their
log-softmax for the backward pass — at GPT-2 vocab (50304) that is the
single largest buffer in the whole train step (3.3 GB at B=16, S=1024)
and caps the batch size far below what the rest of the model needs to
saturate the MXU.

This op computes per-token ``nll = logsumexp(x @ head) - (x @ head)[t]``
in sequence chunks under ``lax.scan`` and registers a custom VJP that
*recomputes* each chunk's logits in the backward pass instead of saving
them:

- forward residuals: ``x`` (bf16, B·S·D), ``head``, ``targets`` and the
  per-token ``lse`` (fp32, B·S) — no (N, V) buffer survives the scan;
- backward: per chunk, ``dlogits = (softmax - onehot) * dnll`` feeds the
  two head matmuls (dx, dhead) directly, fp32 accumulation on the MXU;
- extra cost is one logits recompute (+2·B·S·D·V FLOPs, ~3% of a 125M
  step) traded for gigabytes of HBM — the classic TPU trade.

Sharding contract (found by benchmarks/audit_collectives.py): the scan
chunks along the SEQUENCE axis and keeps the batch axis whole, all ops
rank-3. An earlier version flattened ``(B, S) → rows`` and chunked the
rows — merging the dp/fsdp-sharded batch dim into the row dim, which
made the SPMD partitioner all-gather the hidden states (and tokens)
across data parallel ranks every step: at GPT-2 125M scale, hundreds
of MB of ICI traffic per step that the dense head never paid. With
batch-axis-preserving chunks the partitioned loss is computed entirely
on local shards and the only collectives in a DDP step are the
gradient all-reduces (pinned by tests/test_benchmarks.py::
test_ddp_step_collectives_are_grad_allreduce_only).

No reference counterpart (its models are Linear stubs and its loss is
the degenerate ``F.cross_entropy`` of src/distributed_trainer.py:163;
SURVEY.md §8 B5) — this exists to hit the BASELINE.json MFU target.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

DEFAULT_CHUNK_ROWS = 2048


def _seq_chunk(batch: int, seq: int, chunk_rows: int) -> int:
    """Sequence positions per scan step so that ``B * sc`` ≈ the
    requested row budget (the only (rows, V) fp32 buffer alive)."""
    return max(1, min(seq, chunk_rows // max(batch, 1)))


def _pad_seq(x: jax.Array, t: jax.Array, sc: int):
    """Pad the sequence axis to a multiple of ``sc``; padded targets
    are −1 (masked out downstream)."""
    B, S = t.shape
    pad = (-S) % sc
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((B, pad, x.shape[2]), x.dtype)], axis=1)
        t = jnp.concatenate(
            [t, jnp.full((B, pad), -1, t.dtype)], axis=1)
    return x, t


def _to_chunks(a: jax.Array, sc: int) -> jax.Array:
    """(B, S, ...) → (C, B, sc, ...): split the (replicated-sharding)
    sequence axis and scan over it; the batch axis stays whole so a
    dp/fsdp-sharded batch never crosses a reshape boundary."""
    B, S = a.shape[0], a.shape[1]
    rest = a.shape[2:]
    return jnp.moveaxis(a.reshape(B, S // sc, sc, *rest), 1, 0)


def _from_chunks(a: jax.Array) -> jax.Array:
    """(C, B, sc, ...) → (B, C·sc, ...)."""
    C, B, sc = a.shape[0], a.shape[1], a.shape[2]
    return jnp.moveaxis(a, 0, 1).reshape(B, C * sc, *a.shape[3:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lm_xent_bsd(x, head, t, sc):
    nll, _ = _fwd_scan(x, head, t, sc)
    return nll


def _fwd_scan(x, head, t, sc):
    xc, tc = _to_chunks(x, sc), _to_chunks(t, sc)

    def body(_, inp):
        xb, tb = inp                        # (B, sc, D), (B, sc)
        logits = jax.lax.dot_general(
            xb, head, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (B, sc, V) fp32
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(
            jnp.exp(logits - m[..., None]), axis=-1))
        tgt = jnp.take_along_axis(
            logits, jnp.maximum(tb, 0)[..., None], axis=-1)[..., 0]
        nll = jnp.where(tb >= 0, lse - tgt, 0.0)
        return 0, (nll, lse)

    _, (nll, lse) = jax.lax.scan(body, 0, (xc, tc))
    return _from_chunks(nll), lse            # (B, S_p), (C, B, sc)


def _lm_xent_fwd(x, head, t, sc):
    nll, lse = _fwd_scan(x, head, t, sc)
    return nll, (x, head, t, lse)


def _lm_xent_bwd(sc, res, dnll):
    x, head, t, lse = res

    def body(dhead_acc, inp):
        xb, tb, db, lb = inp                 # (B, sc, *), lb (B, sc)
        logits = jax.lax.dot_general(
            xb, head, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # recomputed
        p = jnp.exp(logits - lb[..., None])          # softmax, fp32
        valid = (tb >= 0)
        onehot = jax.nn.one_hot(jnp.maximum(tb, 0), head.shape[1],
                                dtype=jnp.float32)
        g = jnp.where(valid, db, 0.0).astype(jnp.float32)
        dlogits = ((p - onehot) * g[..., None]).astype(x.dtype)
        dxb = jax.lax.dot_general(
            dlogits, head, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        dhead_acc = dhead_acc + jax.lax.dot_general(
            xb, dlogits, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)      # (D, V)
        return dhead_acc, dxb

    dhead, dx = jax.lax.scan(
        body, jnp.zeros(head.shape, jnp.float32),
        (_to_chunks(x, sc), _to_chunks(t, sc),
         _to_chunks(dnll, sc), lse))
    return (_from_chunks(dx), dhead.astype(head.dtype), None)


_lm_xent_bsd.defvjp(_lm_xent_fwd, _lm_xent_bwd)


def lm_cross_entropy(x: jax.Array, head: jax.Array, targets: jax.Array,
                     chunk_rows: int = DEFAULT_CHUNK_ROWS) -> jax.Array:
    """Per-token LM loss without an (N, V) residual.

    Args:
      x: final hidden states ``(B, S, D)`` (any float dtype; matmuls
        accumulate fp32 on the MXU).
      head: unembedding ``(D, V)``.
      targets: int token ids ``(B, S)``; negative ids are masked (their
        nll and gradient contribution are exactly zero).
      chunk_rows: approximate rows per scan step — the per-step
        ``(B, sc, V)`` fp32 logits buffer holds ``B·sc ≈ chunk_rows``
        rows (sequence-chunked; the batch axis is never split, see the
        sharding contract in the module docstring).

    Returns per-token nll ``(B, S)`` fp32.
    """
    b, s, d = x.shape
    sc = _seq_chunk(b, s, chunk_rows)
    xp, tp = _pad_seq(x, targets, sc)
    nll = _lm_xent_bsd(xp, head, tp, sc)
    return nll[:, :s]
