"""Attention implementations.

- ``naive``: straightforward XLA attention (einsum softmax einsum) — the
  numerics reference every kernel is tested against. XLA already fuses
  this competently on TPU; it is the correctness baseline, not a toy.
- ``flash``: Pallas blockwise-softmax kernel (ops/flash_attention.py) —
  O(S) memory, MXU-tiled; used for long sequences / big models.
- ``ring``: sequence-parallel ring attention (parallel/ring_attention.py)
  — KV blocks rotate around the ``sp`` mesh axis via collective permute.

The reference repo has no attention at all (models are Linear;
SURVEY.md §5.7) — this module exists for the BASELINE.json transformer
targets where MFU ≥ 0.4 requires a real attention path.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from einops import rearrange


def _naive_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     causal: bool = True,
                     segment_mask: jax.Array | None = None,
                     window: int = 0) -> jax.Array:
    """Reference attention. Shapes: q (B, Sq, H, D); k/v (B, Sk, Hkv, D).

    Supports grouped-query attention (Hkv divides H). Softmax in fp32
    regardless of input dtype (bf16-safe), output in q.dtype.
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    group = H // Hkv
    qg = rearrange(q, "b s (hkv g) d -> b s hkv g d", g=group)
    scale = D ** -0.5
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if causal:
        Sk = k.shape[1]
        # Offset alignment: query i attends keys <= i + (Sk - Sq)
        # (supports the ring-attention case where Sq < Sk).
        rows = jnp.arange(Sq)[:, None] + (Sk - Sq)
        cols = jnp.arange(Sk)[None, :]
        mask = cols <= rows
        if window:
            # Sliding window: keys in [i - window + 1, i] only.
            mask = jnp.logical_and(mask, cols >= rows - (window - 1))
        logits = jnp.where(mask[None, None, None], logits, -jnp.inf)
    if segment_mask is not None:
        logits = jnp.where(segment_mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return rearrange(out, "b q hkv g d -> b q (hkv g) d").astype(q.dtype)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool = True,
                          impl: str = "auto",
                          block_q: int | None = None,
                          block_k: int | None = None,
                          window: int = 0,
                          layout: str = "bshd") -> jax.Array:
    """Dispatching attention entrypoint. ``impl``:

    - "auto": flash on TPU when shapes are tile-friendly, else naive
    - "naive" | "flash" | "ring"

    ``block_q``/``block_k`` override the flash kernel's tile sizes
    (None → kernel defaults); ignored by the naive path.
    ``layout="bhsd"``: inputs/outputs are already in the flash
    kernels' (B, H, S, D) layout — no wrapper transposes (the model's
    fast path); the naive fallback transposes at this boundary.
    """
    seq_axis = 2 if layout == "bhsd" else 1
    if impl in ("auto", "flash"):
        from distributed_training_tpu.ops import flash_attention as fa
        # An EXPLICIT tile override that does not divide the sequence
        # must raise, not silently reroute to naive — otherwise sweep
        # rows measure the wrong kernel under the override's label
        # (ADVICE r3; mirrors ring_attention's raise-don't-ignore).
        if impl == "auto" and (block_q or block_k):
            sq, sk = q.shape[seq_axis], k.shape[seq_axis]
            if (block_q and sq % min(block_q, sq)) or (
                    block_k and sk % min(block_k, sk)):
                raise ValueError(
                    f"explicit flash tile override (block_q={block_q}, "
                    f"block_k={block_k}) does not divide seq lengths "
                    f"(Sq={sq}, Sk={sk}); fix the override or pass "
                    "impl='naive' explicitly")
        if fa.supported(q, k, v, block_q=block_q or 0,
                        block_k=block_k or 0,
                        layout=layout) or impl == "flash":
            kw = {}
            if block_q:
                kw["block_q"] = block_q
            if block_k:
                kw["block_k"] = block_k
            return fa.flash_attention(q, k, v, causal=causal,
                                      window=window, layout=layout,
                                      **kw)
        impl = "naive"
    if impl == "naive":
        if layout == "bhsd":
            t = lambda x: jnp.transpose(x, (0, 2, 1, 3))  # noqa: E731
            return t(_naive_attention(t(q), t(k), t(v), causal,
                                      window=window))
        return _naive_attention(q, k, v, causal, window=window)
    raise ValueError(f"unknown attention impl '{impl}'")
