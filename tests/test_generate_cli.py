"""Generation CLI: train → checkpoint → sample, end to end.

The inference half of the transformer story (no reference counterpart
— its models are Linear regressors): the CLI must rebuild the EXACT
trained architecture from the run's resolved_config.yaml, restore the
newest step topology-free, and decode byte-vocab output as text.
"""

import json

import pytest

from distributed_training_tpu import generate as gen_cli
from distributed_training_tpu.train import cli as train_cli


@pytest.fixture(scope="module")
def byte_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("run")
    rc = train_cli.main([
        "model=byte_lm", "train.dataset=synthetic_lm",
        "train.dataset_kwargs={seq_len: 32, vocab_size: 256}",
        "model.kwargs={d_model: 64, n_layers: 2, n_heads: 4, "
        "max_seq_len: 64}",
        "train.total_epochs=1", "train.dataset_size=16",
        "train.batch_size=2", "train.log_every=0",
        "train.save_every=1", "train.dtype=float32",
        f"run.output_dir={out}",
    ])
    assert rc == 0
    return str(out / "default")


def test_generate_from_run_dir_bytes(byte_run, capsys):
    rc = gen_cli.main(["--run-dir", byte_run, "--prompt", "hello",
                       "-n", "8"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "sampled=8" in captured.err
    # Byte-vocab output decodes as text (replacement chars allowed —
    # an untrained model emits arbitrary bytes).
    assert isinstance(captured.out.rstrip("\n"), str)


def test_generate_sampling_reproducible(byte_run, capsys):
    outs = []
    for _ in range(2):
        rc = gen_cli.main(["--run-dir", byte_run, "--prompt", "ab",
                           "-n", "6", "--temperature", "0.9",
                           "--top-k", "10", "--seed", "7"])
        assert rc == 0
        outs.append(capsys.readouterr().out)
    assert outs[0] == outs[1]  # same seed, same sample


def test_generate_prompt_ids_and_validation(byte_run, capsys):
    rc = gen_cli.main(["--run-dir", byte_run, "--prompt-ids",
                       "10,20,30", "-n", "4"])
    assert rc == 0
    del capsys
    with pytest.raises(ValueError, match=r"in \[0, 256\)"):
        gen_cli.main(["--run-dir", byte_run, "--prompt-ids", "999",
                      "-n", "4"])
    with pytest.raises(ValueError, match="empty prompt"):
        gen_cli.main(["--run-dir", byte_run, "--prompt", "", "-n",
                      "4"])


def test_generate_artifact_path_agrees_with_run_dir(byte_run, capsys,
                                                    tmp_path):
    """Two INDEPENDENT restore paths must sample identical greedy
    tokens: the run-dir path (orbax step restore) and a consolidated
    single-file artifact (checkpoint/export.py) — agreement pins both
    against a wrong-subtree/stale-step restore regression."""
    import yaml

    from distributed_training_tpu.checkpoint.export import export

    cfg = gen_cli._load_run_config(byte_run)
    art = tmp_path / "model.msgpack"
    export(cfg.train.snapshot_path, str(art))

    rc = gen_cli.main(["--run-dir", byte_run, "--prompt", "xyz",
                       "-n", "6"])
    assert rc == 0
    out_run = capsys.readouterr().out

    with open(f"{byte_run}/resolved_config.yaml") as f:
        resolved = yaml.safe_load(f)
    kw = dict(resolved["model"]["kwargs"])
    kw["dtype"] = resolved["train"]["dtype"]
    rc = gen_cli.main(["--artifact", str(art),
                       "--model-name", resolved["model"]["name"],
                       "--model-kwargs", json.dumps(kw),
                       "--prompt", "xyz", "-n", "6"])
    assert rc == 0
    out_art = capsys.readouterr().out
    assert out_run == out_art

    # Artifacts are self-describing: no --model-name needed — the
    # architecture meta stamped at save time rebuilds the exact model.
    rc = gen_cli.main(["--artifact", str(art), "--prompt", "xyz",
                       "-n", "6"])
    assert rc == 0
    assert capsys.readouterr().out == out_run

    # --step is meaningless with a single-step artifact: loud error.
    with pytest.raises(ValueError, match="exactly one step"):
        gen_cli.main(["--artifact", str(art), "--step", "3",
                      "--model-name", resolved["model"]["name"],
                      "--prompt", "x"])


def test_generate_moved_run_dir_falls_back_to_local(byte_run, capsys,
                                                    tmp_path):
    """A run dir copied to another machine has a stale absolute
    snapshot_path in its resolved config; the CLI must fall back to
    the checkpoint dir inside the copied run dir itself."""
    import shutil

    import yaml

    moved = tmp_path / "moved_run"
    shutil.copytree(byte_run, moved)
    # Simulate the other machine: the original absolute path is gone.
    with open(moved / "resolved_config.yaml") as f:
        resolved = yaml.safe_load(f)
    resolved["train"]["snapshot_path"] = "/nonexistent/elsewhere/checkpoints"
    with open(moved / "resolved_config.yaml", "w") as f:
        yaml.safe_dump(resolved, f)
    rc = gen_cli.main(["--run-dir", str(moved), "--prompt", "ab",
                       "-n", "4"])
    assert rc == 0
    assert "sampled=4" in capsys.readouterr().err


def test_generate_paged_decode_matches_full_context(byte_run,
                                                    capsys):
    """The serving-KV-cache decode path the CLI now defaults to for
    greedy generation is pinned token-for-token against the ORIGINAL
    full-context discipline: re-run the whole context through
    model.apply for every new token and argmax."""
    import jax.numpy as jnp
    import numpy as np

    # CLI, default (paged) greedy path.
    rc = gen_cli.main(["--run-dir", byte_run, "--prompt", "hello",
                       "-n", "8"])
    assert rc == 0
    out_paged = capsys.readouterr().out.rstrip("\n")

    # Full-context greedy reference on the same restored weights.
    cfg = gen_cli._load_run_config(byte_run)
    model = gen_cli._build_model_from_cfg(cfg)
    params, _step = gen_cli._restore_params(
        byte_run, cfg.train.snapshot_path, None)
    ids = list(np.frombuffer(b"hello", dtype=np.uint8)
               .astype(np.int32))
    ref = []
    for _ in range(8):
        logits, _aux = model.apply(params,
                                   jnp.asarray([ids], jnp.int32))
        t = int(jnp.argmax(logits[0, -1]))
        ref.append(t)
        ids.append(t)
    ref_text = bytes(np.asarray(ref, np.uint8)).decode(
        "utf-8", errors="replace")
    assert out_paged == ref_text

    # The legacy fused dense-cache path agrees too (three decode
    # disciplines, one token stream).
    rc = gen_cli.main(["--run-dir", byte_run, "--decode", "fused",
                       "--prompt", "hello", "-n", "8"])
    assert rc == 0
    assert capsys.readouterr().out.rstrip("\n") == ref_text


def test_eval_cli_scores_checkpoint(byte_run, capsys):
    """Offline eval: the run's own dataset scores to a finite loss,
    and the loss ties back to training (an untrained-vocab-256 model
    sits near ln(256); the trained one must be at or below it)."""
    import math

    from distributed_training_tpu import eval as eval_cli

    rc = eval_cli.main(["--run-dir", byte_run, "--max-batches", "4"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert math.isfinite(rec["loss"])
    assert rec["loss"] <= math.log(256) + 0.2
    assert rec["perplexity"] == pytest.approx(
        math.exp(rec["loss"]), rel=1e-3)
    # dataset_size 16 / (batch 2 x 8 data shards) = 1 global batch.
    assert rec["batches"] == 1
    assert rec["tokens"] == 16 * 33  # 16 rows of seq_len+1 tokens
    assert rec["step"] >= 1


def test_eval_cli_dataset_override(byte_run, capsys):
    from distributed_training_tpu import eval as eval_cli

    rc = eval_cli.main([
        "--run-dir", byte_run, "--dataset", "synthetic_lm",
        "--dataset-kwargs",
        json.dumps({"seq_len": 32, "vocab_size": 256, "size": 8,
                    "seed": 9}),
        "--batch-size", "2"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    # 8 rows < one 16-row global batch on the 8-shard mesh: the
    # padded fallback scores it and SAYS so.
    assert rec["batches"] == 1
    assert rec.get("padded") is True
