"""Exercise infrastructure/gcp/scripts/launch.sh command assembly.

The reference's cloud bootstrap shipped broken-at-launch because nothing
ever executed it (SURVEY.md §8 B1 — cloud-init.tftpl launches an
entrypoint that does not exist). This framework's launcher is therefore
tested, not trusted: a fake ``gcloud`` on PATH records every invocation
and the assertions pin the fan-out flags, the stop-before-launch
ordering, and the double-quoting contract that carries overrides intact
across the two shell hops (local shell → remote login shell → inner
root bash).
"""

import os
import stat
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "infrastructure", "gcp", "scripts",
                      "launch.sh")


def test_launch_sh_bash_syntax():
    """bash -n: the script parses (poor man's shellcheck; the real one
    is not installed in this image)."""
    subprocess.run(["bash", "-n", LAUNCH], check=True)


def _run_with_fake_gcloud(tmp_path, args):
    """Run launch.sh with a PATH-shadowing gcloud that logs its argv
    (NUL-separated so embedded spaces/quotes are reconstructable)."""
    calls = tmp_path / "calls"
    calls.mkdir()
    fake = tmp_path / "bin" / "gcloud"
    fake.parent.mkdir()
    fake.write_text(
        "#!/usr/bin/env bash\n"
        f'f="{calls}/$(date +%s%N)-$$"\n'
        'printf "%s\\0" "$@" > "$f"\n')
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env["PATH"] = f"{fake.parent}:{env['PATH']}"
    proc = subprocess.run(["bash", LAUNCH, *args], env=env,
                          capture_output=True, text=True, timeout=60)
    recorded = []
    for name in sorted(os.listdir(calls)):
        blob = (calls / name).read_bytes().decode()
        recorded.append(blob.rstrip("\0").split("\0"))
    return proc, recorded


def test_launch_sh_two_phase_fanout(tmp_path):
    proc, calls = _run_with_fake_gcloud(
        tmp_path, ["my-pod", "us-central2-b",
                   "train.parallel_strategy=fsdp"])
    assert proc.returncode == 0, proc.stderr
    assert len(calls) == 2, calls

    stop, launch = calls
    for argv in (stop, launch):
        # Pod-wide fan-out over every worker of the named pod.
        assert argv[:6] == ["compute", "tpus", "tpu-vm", "ssh",
                            "my-pod", "--zone"]
        assert argv[6] == "us-central2-b"
        assert "--worker=all" in argv

    # Phase 1 stops (and waits out) any previous trainer; its pkill
    # pattern must not be able to match its own argv (bracket trick).
    stop_cmd = stop[-1]
    assert "pkill" in stop_cmd
    assert "[m]ultigpu_multi_node.py" in stop_cmd
    assert "multigpu_multi_node.py" not in stop_cmd.replace(
        "[m]ultigpu_multi_node.py", "")
    # Phase 2 launches the reference-named entrypoint under nohup.
    launch_cmd = launch[-1]
    assert "multigpu_multi_node.py" in launch_cmd
    assert "DTT_AUTO_DISTRIBUTED=1" in launch_cmd
    assert "train.parallel_strategy=fsdp" in launch_cmd

    # The operator gets the log-tailing hint.
    assert "tail -f /var/log/dtt-train.log" in proc.stdout


def test_launch_sh_overrides_survive_quoting(tmp_path):
    """An override containing spaces and quotes must arrive inside the
    remote bash -c payload still as one argument (%q round-trip)."""
    tricky = "run.experiment_name=my exp\"q'uote"
    proc, calls = _run_with_fake_gcloud(
        tmp_path, ["pod", "zone-x", tricky])
    assert proc.returncode == 0, proc.stderr
    launch_cmd = calls[1][-1]
    # The inner payload is %q-quoted for the remote bash -c. Unwrap it
    # exactly as the remote root shell would and check the argument
    # boundary: a correctly-quoted tricky override parses back to the
    # original string as ONE argv element of the inner command line.
    import re
    m = re.search(r"bash -c (.+)$", launch_cmd, re.M)
    assert m, launch_cmd
    unwrapped = subprocess.run(
        ["bash", "-c", f"printf '%s' {m.group(1).strip()}"],
        capture_output=True, text=True)
    assert tricky in subprocess.run(
        ["bash", "-c",
         f"eval 'set -- '{_shquote(_extract_args(unwrapped.stdout))};"
         " printf '%s\\0' \"$@\""],
        capture_output=True, text=True).stdout.split("\0"), (
        unwrapped.stdout)


def _extract_args(inner_cmd: str) -> str:
    """Pull the override tail of the inner launch line (everything
    after the entrypoint, before the log redirect)."""
    start = inner_cmd.index("multigpu_multi_node.py") + len(
        "multigpu_multi_node.py")
    end = inner_cmd.index(" > /var/log/")
    return inner_cmd[start:end]


def _shquote(s: str) -> str:
    import shlex
    return shlex.quote(s)


def test_launch_sh_usage_errors():
    proc = subprocess.run(["bash", LAUNCH], capture_output=True,
                          text=True)
    assert proc.returncode != 0
    assert "usage:" in proc.stderr
    proc = subprocess.run(["bash", LAUNCH, "pod-only"],
                          capture_output=True, text=True)
    assert proc.returncode != 0
    assert "usage:" in proc.stderr
