"""Resilience subsystem: crash-restart-resume end to end.

Unit tests for the three pillars (supervisor budget/backoff/exit
classification, checkpoint integrity manifests + quarantine +
fallback chain, deterministic fault-plan parsing and injection), the
satellite behaviors (launcher signal forwarding, context-managed
checkpointer, loader retry), and the CPU e2e the ISSUE demands: a
``crash@N`` fault under ``--supervise`` restarts, resumes from the
last good checkpoint, and finishes with state identical to an
uninterrupted run; a deliberate crash-loop exhausts the budget and
exits nonzero.
"""

import json
import os
import signal
import threading
import time

import jax
import numpy as np
import pytest

from distributed_training_tpu import telemetry
from distributed_training_tpu.checkpoint import Checkpointer
from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.launch import local as launch_local_mod
from distributed_training_tpu.models.mlp import MLP
from distributed_training_tpu.resilience import faults, integrity
from distributed_training_tpu.resilience import supervisor as sup
from distributed_training_tpu.train.trainer import Trainer


@pytest.fixture(autouse=True)
def _fresh_ambient():
    telemetry.uninstall()
    yield
    telemetry.uninstall()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- supervisor: backoff --------------------------------------------------


def test_backoff_exponential_capped_jittered():
    p = sup.RestartPolicy(backoff_base_s=1.0, backoff_factor=2.0,
                          backoff_max_s=10.0, jitter=0.2, seed=3)
    # Within +/-20% of the exponential schedule, capped at max.
    for n, base in [(1, 1.0), (2, 2.0), (3, 4.0), (4, 8.0),
                    (5, 10.0), (9, 10.0)]:
        d = p.backoff_s(n)
        assert 0.8 * base <= d <= 1.2 * base, (n, d)
    # Deterministic for a given seed; a different seed jitters apart.
    assert p.backoff_s(2) == p.backoff_s(2)
    p2 = sup.RestartPolicy(backoff_base_s=1.0, jitter=0.2, seed=4)
    assert p.backoff_s(2) != p2.backoff_s(2)


def test_backoff_zero_jitter_exact():
    p = sup.RestartPolicy(backoff_base_s=0.5, backoff_factor=3.0,
                          backoff_max_s=100.0, jitter=0.0)
    assert [p.backoff_s(n) for n in (1, 2, 3)] == [0.5, 1.5, 4.5]


# -- supervisor: exit classification --------------------------------------


def test_classify_exit_precedence():
    # rc 0, no sentinel: completed (process too old to write one).
    assert sup.classify_exit(0, []) == sup.COMPLETED
    # rc 0 + preempted sentinel: the ONLY way to tell these apart.
    assert sup.classify_exit(
        0, [{"outcome": sup.PREEMPTED}]) == sup.PREEMPTED
    assert sup.classify_exit(
        0, [{"outcome": sup.COMPLETED}]) == sup.COMPLETED
    # Watchdog abort wins over everything, by sentinel or by rc 42.
    assert sup.classify_exit(
        1, [{"outcome": sup.WATCHDOG_ABORT}]) == sup.WATCHDOG_ABORT
    assert sup.classify_exit(sup.WATCHDOG_EXIT_CODE,
                             []) == sup.WATCHDOG_ABORT
    # Signal deaths (launcher encodes as 128+signum): preemption shape.
    assert sup.classify_exit(143, []) == sup.PREEMPTED
    assert sup.classify_exit(130, []) == sup.PREEMPTED
    # Anything else nonzero: crash.
    assert sup.classify_exit(1, []) == sup.CRASH
    assert sup.classify_exit(139, []) == sup.CRASH
    # Worst report wins across a multi-process incarnation.
    assert sup.classify_exit(0, [{"outcome": sup.COMPLETED},
                                 {"outcome": sup.PREEMPTED}]) \
        == sup.PREEMPTED
    # ...including when one process reports preempted but the group rc
    # is crash-shaped: a preemption verdict would REFUND the budget a
    # real crash must burn.
    assert sup.classify_exit(1, [{"outcome": sup.PREEMPTED}]) \
        == sup.CRASH


def test_exit_sentinel_roundtrip(tmp_path, monkeypatch):
    base = str(tmp_path / "exit_0")
    monkeypatch.setenv(sup.ENV_SENTINEL, base)
    path = sup.write_exit_status(sup.PREEMPTED, step=40)
    assert path and os.path.exists(path)
    recs = sup.read_exit_statuses(base)
    assert len(recs) == 1
    assert recs[0]["outcome"] == sup.PREEMPTED
    assert recs[0]["step"] == 40
    # Unsupervised (no env): a silent no-op, not an error.
    monkeypatch.delenv(sup.ENV_SENTINEL)
    assert sup.write_exit_status(sup.COMPLETED) is None


# -- supervisor: the loop --------------------------------------------------


def _scripted_incarnations(script, ckpt_dir, pid="1"):
    """Fake ``run_incarnation``: each call plays the next
    (rc, sentinel_outcome, new_ckpt_step) entry — writing the exit
    sentinel and fake checkpoint step dir the real launcher's children
    would produce. ``pid`` distinguishes sentinel files the way real
    child pids do across supervisor runs."""
    calls = []

    def run(extra_env):
        i = min(len(calls), len(script) - 1)
        calls.append(dict(extra_env))
        rc, outcome, step = script[i]
        base = extra_env[sup.ENV_SENTINEL]
        if outcome is not None:
            os.makedirs(os.path.dirname(base), exist_ok=True)
            with open(f"{base}.pid{pid}.json", "w") as f:
                json.dump({"outcome": outcome}, f)
        if step is not None:
            os.makedirs(os.path.join(ckpt_dir, str(step)),
                        exist_ok=True)
        return rc

    run.calls = calls
    return run


def test_supervise_completes_first_try(tmp_path):
    run = _scripted_incarnations([(0, sup.COMPLETED, None)],
                                 str(tmp_path / "ckpt"))
    res = sup.supervise(run, state_dir=str(tmp_path / "state"),
                        sleep=lambda s: None)
    assert res.returncode == 0
    assert res.restarts == 0
    assert res.incidents[0].outcome == sup.COMPLETED


def test_supervise_progress_refunds_budget(tmp_path):
    """Two crashes, each having advanced the checkpoint, survive a
    max_restarts=1 budget — DISTINCT failures on a long healthy run
    must not accumulate toward give-up."""
    ckpt = str(tmp_path / "ckpt")
    run = _scripted_incarnations(
        [(1, None, 8), (1, None, 16), (0, sup.COMPLETED, None)], ckpt)
    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=1),
        state_dir=str(tmp_path / "state"), ckpt_dir=ckpt,
        sleep=lambda s: None)
    assert res.returncode == 0
    assert res.restarts == 2
    assert [i.advanced for i in res.incidents] == [True, True, False]
    # Refund: budget back at max after each advancing failure.
    assert [i.budget_after for i in res.incidents] == [1, 1, 1]


def test_supervise_crash_loop_exhausts_budget(tmp_path):
    """No checkpoint progress → every failure burns budget → exactly
    max_restarts+1 incarnations, nonzero rc, give-up event."""
    events = str(tmp_path / "sup_events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=events)
    delays = []
    run = _scripted_incarnations([(1, None, None)],
                                 str(tmp_path / "ckpt"))
    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=2,
                                      backoff_base_s=0.5, jitter=0.0),
        state_dir=str(tmp_path / "state"),
        ckpt_dir=str(tmp_path / "ckpt"),
        telemetry=tel, sleep=delays.append)
    tel.close()
    assert res.returncode == 1
    assert len(res.incidents) == 3  # max_restarts + 1
    assert res.incidents[-1].budget_after == -1
    # Backoff escalated between non-advancing failures.
    assert delays == [0.5, 1.0]
    kinds = [e["kind"] for e in _read_jsonl(events)]
    assert kinds.count("restart") == 2
    assert "supervisor_give_up" in kinds
    # The give-up summary names every incarnation.
    assert len(res.summary_lines()) == 1 + 3


def test_supervise_preemption_refunds_and_restarts(tmp_path):
    """A clean preemption is the infrastructure's fault, not the
    job's: it refunds budget and restarts (supervisor not stopped)."""
    run = _scripted_incarnations(
        [(0, sup.PREEMPTED, None), (0, sup.COMPLETED, None)],
        str(tmp_path / "ckpt"))
    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=0),
        state_dir=str(tmp_path / "state"), sleep=lambda s: None)
    assert res.returncode == 0
    assert res.restarts == 1
    assert res.incidents[0].outcome == sup.PREEMPTED
    assert res.incidents[0].budget_after == 0  # refunded to max (0)


def test_supervise_preemption_storm_backs_off(tmp_path):
    """Preemptions without checkpoint progress keep refunding the
    budget (unbounded retries are the point) but the backoff must
    escalate — never a hot restart loop."""
    run = _scripted_incarnations(
        [(0, sup.PREEMPTED, None), (0, sup.PREEMPTED, None),
         (0, sup.PREEMPTED, None), (0, sup.COMPLETED, None)],
        str(tmp_path / "ckpt"))
    delays = []
    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=1,
                                      backoff_base_s=0.5, jitter=0.0),
        state_dir=str(tmp_path / "state"), sleep=delays.append)
    assert res.returncode == 0
    assert delays == [0.5, 1.0, 2.0]
    assert all(i.budget_after == 1 for i in res.incidents[:3])


def test_supervise_stop_requested_stands_down(tmp_path):
    """When the LAUNCHER itself was signaled, the supervisor must not
    restart the job the infrastructure just reclaimed."""
    run = _scripted_incarnations([(0, sup.PREEMPTED, None)],
                                 str(tmp_path / "ckpt"))
    res = sup.supervise(run, state_dir=str(tmp_path / "state"),
                        should_stop=lambda: True,
                        sleep=lambda s: None)
    assert len(res.incidents) == 1
    assert len(run.calls) == 1


def test_supervise_progress_survives_quarantine_lowered_step(tmp_path):
    """A restore-time quarantine LOWERS the latest on-disk step; an
    incarnation that then saves a NEW (but numerically lower) step is
    real progress and must refund — an all-time high-water comparison
    would burn budget on a recovering run."""
    ckpt = str(tmp_path / "ckpt")
    for s in ("100", "110"):
        os.makedirs(os.path.join(ckpt, s))
    calls = []

    def run(extra_env):
        calls.append(dict(extra_env))
        if len(calls) == 1:
            # The child's restore quarantined damaged step 110 and
            # the run re-saved at 105 before crashing again.
            os.rename(os.path.join(ckpt, "110"),
                      os.path.join(ckpt, "step_110.corrupt"))
            os.makedirs(os.path.join(ckpt, "105"))
            return 1
        base = extra_env[sup.ENV_SENTINEL]
        with open(f"{base}.pid1.json", "w") as f:
            json.dump({"outcome": sup.COMPLETED}, f)
        return 0

    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=0),
        state_dir=str(tmp_path / "state"), ckpt_dir=ckpt,
        sleep=lambda s: None)
    assert res.returncode == 0
    assert res.incidents[0].advanced  # 105 is NEW, despite 105 < 110
    assert res.incidents[0].budget_after == 0  # refunded to max (0)


def test_supervise_ignores_stale_sentinels_from_previous_run(tmp_path):
    """Log dirs default to a constant path, so supervisor state_dirs
    get reused across runs; a previous run's sentinels (different
    pids) must not leak into this run's exit classification."""
    state = str(tmp_path / "state")
    run1 = _scripted_incarnations(
        [(sup.WATCHDOG_EXIT_CODE, sup.WATCHDOG_ABORT, None)],
        str(tmp_path / "ckpt"), pid="111")
    res1 = sup.supervise(run1,
                         policy=sup.RestartPolicy(max_restarts=0),
                         state_dir=state, sleep=lambda s: None)
    assert res1.returncode != 0  # watchdog-abort crash loop, gave up
    # Same state_dir, new run (new pids): completes first try — the
    # stale watchdog_abort sentinel at index 0 must not burn budget.
    run2 = _scripted_incarnations([(0, sup.COMPLETED, None)],
                                  str(tmp_path / "ckpt"), pid="222")
    res2 = sup.supervise(run2,
                         policy=sup.RestartPolicy(max_restarts=0),
                         state_dir=state, sleep=lambda s: None)
    assert res2.returncode == 0
    assert res2.incidents[0].outcome == sup.COMPLETED


def test_supervise_classifies_watchdog_abort(tmp_path):
    run = _scripted_incarnations(
        [(sup.WATCHDOG_EXIT_CODE, None, None),
         (0, sup.COMPLETED, None)], str(tmp_path / "ckpt"))
    res = sup.supervise(
        run, policy=sup.RestartPolicy(max_restarts=1),
        state_dir=str(tmp_path / "state"), sleep=lambda s: None)
    assert res.returncode == 0
    assert res.incidents[0].outcome == sup.WATCHDOG_ABORT


# -- integrity: manifests --------------------------------------------------


def _make_step_dir(tmp_path, step=8, payload=b"x" * 4096):
    d = tmp_path / str(step)
    (d / "state").mkdir(parents=True)
    (d / "state" / "arrays.bin").write_bytes(payload)
    (d / "meta.json").write_text('{"epoch": 1}')
    return str(d)


def test_manifest_roundtrip_and_damage_detection(tmp_path):
    d = _make_step_dir(tmp_path)
    integrity.write_manifest(d)
    assert integrity.verify_manifest(d) == (True, [])
    # Same-size content damage: caught by checksum.
    faults.corrupt_step_dir(d)
    verified, problems = integrity.verify_manifest(d)
    assert verified and any("checksum mismatch" in p for p in problems)


def test_manifest_catches_missing_extra_resized(tmp_path):
    d = _make_step_dir(tmp_path)
    integrity.write_manifest(d)
    os.remove(os.path.join(d, "meta.json"))
    with open(os.path.join(d, "state", "arrays.bin"), "ab") as f:
        f.write(b"tail")
    with open(os.path.join(d, "state", "extra.bin"), "wb") as f:
        f.write(b"new")
    _, problems = integrity.verify_manifest(d)
    text = "\n".join(problems)
    assert "missing file: meta.json" in text
    assert "unexpected file: state/extra.bin" in text
    assert "size mismatch: state/arrays.bin" in text


def test_manifest_absent_is_unverified_not_condemned(tmp_path):
    d = _make_step_dir(tmp_path)
    assert integrity.verify_manifest(d) == (False, [])


def test_unreadable_manifest_condemns(tmp_path):
    d = _make_step_dir(tmp_path)
    with open(os.path.join(d, integrity.MANIFEST_NAME), "w") as f:
        f.write("{not json")
    verified, problems = integrity.verify_manifest(d)
    assert verified and problems


def test_step_scan_ignores_non_numeric_and_quarantined(tmp_path):
    for name in ("8", "16", "24"):
        (tmp_path / name).mkdir()
    (tmp_path / "16.orbax-checkpoint-tmp-123").mkdir()
    (tmp_path / "step_24.corrupt").mkdir()
    (tmp_path / "consolidated_step24.msgpack").write_bytes(b"")
    assert integrity.checkpoint_steps_on_disk(str(tmp_path)) == \
        [8, 16, 24]
    assert integrity.latest_step_on_disk(str(tmp_path)) == 24
    assert integrity.latest_step_on_disk(
        str(tmp_path / "nonexistent")) is None


def test_quarantine_renames_and_survives_collisions(tmp_path):
    _make_step_dir(tmp_path, step=8)
    dst = integrity.quarantine_step(str(tmp_path), 8, ["bad"])
    assert dst.endswith("step_8.corrupt") and os.path.isdir(dst)
    assert not os.path.exists(tmp_path / "8")
    # A later incarnation condemning a NEW step 8 must not collide.
    _make_step_dir(tmp_path, step=8)
    dst2 = integrity.quarantine_step(str(tmp_path), 8, ["bad again"])
    assert dst2.endswith("step_8.corrupt.2")
    # Step already gone (lost the rename race): not an error.
    assert integrity.quarantine_step(str(tmp_path), 8) is None


# -- faults: plan parsing --------------------------------------------------


def test_fault_plan_full_grammar():
    plan = faults.parse_fault_plan(
        "crash@40,sigterm@80,corrupt_ckpt@120,"
        "data_stall@60:500ms,data_error@70,crash@90:always")
    by_key = {f.key: f for f in plan}
    assert by_key["crash@40"].always is False
    assert by_key["crash@90"].always is True
    assert by_key["data_stall@60"].stall_s == 0.5
    assert by_key["sigterm@80"].step == 80
    # Empty entries (trailing comma) tolerated; empty plan is empty.
    assert faults.parse_fault_plan("crash@40,") == \
        faults.parse_fault_plan("crash@40")
    assert faults.parse_fault_plan("") == ()


@pytest.mark.parametrize("bad", [
    "crash",                  # no @step
    "crash@",                 # no step
    "meteor@40",              # unknown kind
    "crash@0",                # step must be >= 1
    "crash@40,crash@40",      # duplicate incident
    "data_stall@60",          # stall needs a duration
    "crash@40:500ms",         # duration on a non-stall fault
    "data_stall@60:500",      # unitless duration
])
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(faults.FaultPlanError):
        faults.parse_fault_plan(bad)


def test_parse_duration():
    assert faults.parse_duration_s("500ms") == 0.5
    assert faults.parse_duration_s("2s") == 2.0
    assert faults.parse_duration_s("1.5s") == 1.5
    with pytest.raises(faults.FaultPlanError):
        faults.parse_duration_s("5m")


# -- faults: injector ------------------------------------------------------


def test_injector_crash_is_one_shot_across_restarts(tmp_path):
    ledger = str(tmp_path / "faults_fired.json")
    inj = faults.FaultInjector("crash@5", ledger_path=ledger)
    inj.on_step(4)  # not due yet
    with pytest.raises(faults.InjectedCrash):
        inj.on_step(5)
    # The ledger was written BEFORE the raise: a restarted injector
    # (new process, same ledger) replaying step 5 must not re-fire.
    inj2 = faults.FaultInjector("crash@5", ledger_path=ledger)
    inj2.on_step(5)
    assert inj2.fired == {"crash@5"}


def test_injector_always_refires(tmp_path):
    ledger = str(tmp_path / "faults_fired.json")
    for _ in range(2):  # every "incarnation" crashes again
        inj = faults.FaultInjector("crash@5:always",
                                   ledger_path=ledger)
        with pytest.raises(faults.InjectedCrash):
            inj.on_step(5)


def test_injector_sigterm_delivers_signal():
    got = []
    prev = signal.signal(signal.SIGTERM,
                         lambda s, f: got.append(s))
    try:
        inj = faults.FaultInjector("sigterm@3")
        inj.on_step(3)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert got == [signal.SIGTERM]


def test_injector_data_error_and_stall(tmp_path):
    inj = faults.FaultInjector("data_error@2,data_stall@3:10ms")
    inj.on_data(1)
    with pytest.raises(faults.InjectedDataError):
        inj.on_data(2)
    assert isinstance(faults.InjectedDataError("x"), OSError)
    t0 = time.monotonic()
    inj.on_data(3)  # sleeps 10ms
    assert time.monotonic() - t0 >= 0.01
    inj.on_data(3)  # one-shot: no second stall
    assert inj.fired == {"data_error@2", "data_stall@3"}


def test_injector_corrupts_latest_committed_checkpoint(tmp_path):
    step_dir = _make_step_dir(tmp_path, step=8)
    integrity.write_manifest(step_dir)
    inj = faults.FaultInjector("corrupt_ckpt@5",
                               ckpt_dir=str(tmp_path))
    # Fires at the FIRST save with step >= 5, not an exact match.
    inj.on_checkpoint_saved(8)
    _, problems = integrity.verify_manifest(step_dir)
    assert problems, "injected corruption not detected by manifest"
    assert inj.fired == {"corrupt_ckpt@5"}


def test_injector_only_corrupts_manifested_steps(tmp_path):
    """Damaging a not-yet-manifested step would let the later
    manifest flush checksum the corrupted bytes and BLESS them; the
    injector must target the newest MANIFESTED step (and stay armed
    while none exists)."""
    unmanifested = _make_step_dir(tmp_path, step=16)
    inj = faults.FaultInjector("corrupt_ckpt@5",
                               ckpt_dir=str(tmp_path))
    inj.on_checkpoint_saved(16)
    assert inj.fired == set()  # no eligible victim yet: stays armed
    manifested = _make_step_dir(tmp_path, step=8)
    integrity.write_manifest(manifested)
    inj.on_checkpoint_saved(24)
    assert inj.fired == {"corrupt_ckpt@5"}
    # The older-but-manifested step took the damage...
    _, problems = integrity.verify_manifest(manifested)
    assert problems
    # ...and the unmanifested one is untouched.
    assert integrity.verify_manifest(unmanifested) == (False, [])


def test_async_checkpointer_corruption_is_always_detectable(tmp_path):
    """End-to-end ordering with ASYNC saves (the CLI default): the
    fault must land on a step whose manifest predates the damage, so
    verification catches it — never a step manifested afterwards."""
    state = {"a": np.arange(64, dtype=np.float32)}
    inj = faults.FaultInjector("corrupt_ckpt@1",
                               ledger_path=str(tmp_path / "led.json"))
    with Checkpointer(str(tmp_path / "ckpt"), async_save=True,
                      fault_injector=inj) as ckpt:
        assert ckpt.save(1, state, meta={"epoch": 0})
        # save(1) is async: step 1 has no manifest yet, so the fault
        # stays armed instead of corrupting a future-blessed step.
        assert inj.fired == set()
        assert ckpt.save(2, state, meta={"epoch": 1})
        # save(2) committed+manifested step 1 first; THEN the fault
        # fired against it.
        assert inj.fired == {"corrupt_ckpt@1"}
    d1 = str(tmp_path / "ckpt" / "1")
    d2 = str(tmp_path / "ckpt" / "2")
    _, problems = integrity.verify_manifest(d1)
    assert problems, "corruption blessed by a post-damage manifest"
    assert integrity.verify_manifest(d2) == (True, [])


# -- checkpointer: integrity + fallback chain (real orbax) ----------------


def _build_trainer(rt, tmp_path, epochs=3):
    cfg = Config()
    cfg.train.total_epochs = epochs
    cfg.train.save_every = 1
    cfg.train.batch_size = 4
    cfg.train.dataset_size = 64
    cfg.train.log_every = 0
    cfg.train.snapshot_path = str(tmp_path / "ckpt")
    ds = SyntheticRegressionDataset(size=64, seed=0, kind="linear")
    loader = ShardedDataLoader(ds, rt, batch_size=4,
                               seed=cfg.train.seed)
    model = MLP(input_size=20, output_size=1)
    ckpt = Checkpointer(cfg.train.snapshot_path, async_save=False)
    return Trainer(cfg, rt, model, loader, ckpt), ckpt, cfg


def test_saves_write_manifests(cpu8, tmp_path):
    trainer, ckpt, _ = _build_trainer(cpu8, tmp_path, epochs=2)
    trainer.train()
    ckpt.close()
    steps = integrity.checkpoint_steps_on_disk(str(tmp_path / "ckpt"))
    assert steps, "no checkpoints written"
    for step in steps:
        d = str(tmp_path / "ckpt" / str(step))
        assert integrity.verify_manifest(d) == (True, []), step


def test_restore_falls_back_past_corrupt_latest(cpu8, tmp_path):
    """The acceptance scenario: latest checkpoint deliberately
    corrupted → restore quarantines it (event emitted) and resumes
    from the previous good step instead of raising."""
    trainer, ckpt, _ = _build_trainer(cpu8, tmp_path, epochs=3)
    trainer.train()
    steps = integrity.checkpoint_steps_on_disk(str(tmp_path / "ckpt"))
    ckpt.close()
    faults.corrupt_step_dir(str(tmp_path / "ckpt" / str(steps[-1])))

    events = str(tmp_path / "events.jsonl")
    telemetry.install(telemetry.Telemetry(events_jsonl=events))
    trainer2, ckpt2, _ = _build_trainer(cpu8, tmp_path, epochs=3)
    ckpt2.close()
    # Resumed from the NEXT-OLDER good step, not fresh.
    assert int(trainer2.state["step"]) == steps[-2]
    assert trainer2.epochs_run == 2
    # The condemned step is quarantined, not deleted.
    assert os.path.isdir(
        tmp_path / "ckpt" / f"step_{steps[-1]}.corrupt")
    assert not os.path.exists(tmp_path / "ckpt" / str(steps[-1]))
    quar = [e for e in _read_jsonl(events)
            if e["kind"] == "ckpt_quarantined"]
    assert len(quar) == 1 and quar[0]["step"] == steps[-1]
    assert quar[0]["problems"]


def test_restore_fresh_start_when_every_step_corrupt(cpu8, tmp_path):
    trainer, ckpt, _ = _build_trainer(cpu8, tmp_path, epochs=2)
    trainer.train()
    ckpt.close()
    steps = integrity.checkpoint_steps_on_disk(str(tmp_path / "ckpt"))
    for s in steps:
        faults.corrupt_step_dir(str(tmp_path / "ckpt" / str(s)))
    trainer2, ckpt2, _ = _build_trainer(cpu8, tmp_path, epochs=2)
    ckpt2.close()
    assert trainer2.epochs_run == 0
    assert int(trainer2.state["step"]) == 0
    assert integrity.checkpoint_steps_on_disk(
        str(tmp_path / "ckpt")) == []


def test_restore_quarantines_on_orbax_failure(cpu8, tmp_path):
    """A step whose manifest is gone AND whose payload orbax cannot
    read (legacy checkpoint damaged in place) falls back via the
    restore-exception path, not a crash."""
    import shutil
    trainer, ckpt, _ = _build_trainer(cpu8, tmp_path, epochs=2)
    trainer.train()
    ckpt.close()
    steps = integrity.checkpoint_steps_on_disk(str(tmp_path / "ckpt"))
    latest = str(tmp_path / "ckpt" / str(steps[-1]))
    os.remove(os.path.join(latest, integrity.MANIFEST_NAME))
    shutil.rmtree(os.path.join(latest, "state"))
    trainer2, ckpt2, _ = _build_trainer(cpu8, tmp_path, epochs=2)
    ckpt2.close()
    assert int(trainer2.state["step"]) == steps[-2]
    assert os.path.isdir(
        tmp_path / "ckpt" / f"step_{steps[-1]}.corrupt")


def test_checkpointer_context_manager_drains_async_save(tmp_path):
    """__exit__ must wait() (manifests flushed, save durable) and
    close() on every exit path — here the normal one."""
    state = {"a": np.arange(32, dtype=np.float32)}
    with Checkpointer(str(tmp_path / "ckpt"),
                      async_save=True) as ckpt:
        assert ckpt.save(1, state, meta={"epoch": 0})
    d = str(tmp_path / "ckpt" / "1")
    assert os.path.isdir(d)
    assert integrity.verify_manifest(d) == (True, [])


# -- loader: bounded retry -------------------------------------------------


def _tiny_loader(rt, **kw):
    ds = SyntheticRegressionDataset(size=32, seed=0, kind="linear")
    return ShardedDataLoader(ds, rt, batch_size=4, shuffle=False,
                             prefetch_depth=0, **kw)


def test_loader_retries_transient_errors(cpu8, tmp_path):
    events = str(tmp_path / "events.jsonl")
    telemetry.install(telemetry.Telemetry(events_jsonl=events))
    loader = _tiny_loader(cpu8, data_retries=2)
    real = loader._assemble
    blips = {"left": 2}

    def flaky(rows):
        if blips["left"]:
            blips["left"] -= 1
            raise OSError("synthetic io blip")
        return real(rows)

    loader._assemble = flaky
    batches = list(loader.epoch(0))
    assert len(batches) == loader.steps_per_epoch
    retries = [e for e in _read_jsonl(events)
               if e["kind"] == "data_retry"]
    assert len(retries) == 2
    assert retries[0]["attempt"] == 1 and retries[1]["attempt"] == 2
    assert "OSError" in retries[0]["error"]


def test_loader_retry_budget_exhausts(cpu8):
    loader = _tiny_loader(cpu8, data_retries=1)

    def always_fails(rows):
        raise OSError("persistent failure")

    loader._assemble = always_fails
    with pytest.raises(OSError, match="persistent failure"):
        list(loader.epoch(0))


def test_loader_fatal_errors_not_retried(cpu8):
    loader = _tiny_loader(cpu8, data_retries=5)
    calls = {"n": 0}

    def malformed(rows):
        calls["n"] += 1
        raise ValueError("malformed sample")

    loader._assemble = malformed
    with pytest.raises(ValueError):
        list(loader.epoch(0))
    assert calls["n"] == 1  # no retry: bad data won't improve


def test_loader_injected_data_error_recovers(cpu8, tmp_path):
    """The fault hook runs INSIDE the retry loop: an injected
    transient exercises exactly the real recovery path."""
    inj = faults.FaultInjector(
        "data_error@1", ledger_path=str(tmp_path / "ledger.json"))
    loader = _tiny_loader(cpu8, data_retries=2, fault_injector=inj)
    batches = list(loader.epoch(0))
    assert len(batches) == loader.steps_per_epoch
    assert inj.fired == {"data_error@1"}


# -- launcher: signal forwarding ------------------------------------------


def test_wait_forwards_sigterm_to_children(tmp_path):
    """When the LAUNCHER is signaled mid-wait, children must receive
    the signal (their PreemptionGuard path) and the launcher reaps
    them cleanly instead of orphaning them."""
    ready = tmp_path / "handler_installed"
    procs = launch_local_mod.launch_local(
        ["-c",
         "import signal, sys, time\n"
         "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
         f"open({str(ready)!r}, 'w').close()\n"
         "time.sleep(600)\n"],
        num_processes=1, log_dir=str(tmp_path))

    def _signal_when_ready():
        # Signal only after the child has INSTALLED its handler — a
        # fixed pre-signal delay races python startup under suite
        # load, and a child killed by default SIGTERM (-15) is a
        # startup race, not the forwarding bug this test guards.
        deadline = time.time() + 30
        while not ready.exists() and time.time() < deadline:
            time.sleep(0.02)
        signal.raise_signal(signal.SIGTERM)

    timer = threading.Thread(target=_signal_when_ready, daemon=True)
    timer.start()
    try:
        code = launch_local_mod.wait(procs, timeout=60)
    finally:
        timer.join(timeout=35)
        launch_local_mod._launcher_signaled = False
    assert code == 0  # child exited 0 FROM ITS HANDLER, not killed


# -- summarizer: recovery accounting --------------------------------------


def test_recovery_counts_fresh_start_restart():
    """A crash BEFORE the first checkpoint restarts into a fresh
    incarnation (resume at step 0) — still an incident; only
    resume-less appended sessions (offline eval) are excluded."""
    from distributed_training_tpu.telemetry.summarize import _recovery
    events = [
        {"kind": "run_start", "t": 100.0, "step": 0},
        {"kind": "span", "t": 105.0, "name": "step", "step": 10},
        {"kind": "run_start", "t": 120.0, "step": 0},
        {"kind": "resume", "t": 121.0, "step": 0, "restarts": 1},
    ]
    rec = _recovery(events)
    assert rec["restarts"] == 1
    inc = rec["incidents"][0]
    assert inc["resumed_at_step"] == 0
    assert inc["steps_lost"] == 10
    assert inc["time_to_recover_s"] == 15.0
    # An appended session with no resume (offline eval) is NOT one.
    rec2 = _recovery(events + [
        {"kind": "run_start", "t": 300.0, "step": 20},
        {"kind": "eval_result", "t": 301.0, "loss": 1.0},
    ])
    assert rec2["restarts"] == 1


def test_recovery_exactly_once_columns_from_cursor():
    """Resume events carrying the loader cursor (docs/data.md) add
    samples-replayed / samples-skipped / mixture-drift columns to the
    incident — additive keys; cursor-less resume events keep the old
    incident shape."""
    from distributed_training_tpu.telemetry.summarize import (
        _recovery, render_recovery_lines)

    def run(resume_extra):
        return _recovery([
            {"kind": "run_start", "t": 100.0, "step": 0},
            {"kind": "span", "t": 105.0, "name": "step", "step": 12},
            {"kind": "run_start", "t": 120.0, "step": 10},
            {"kind": "resume", "t": 121.0, "step": 10, "restarts": 1,
             **resume_extra},
        ])["incidents"][0]

    # Exactly-once: cursor == step * global_batch -> 0 / 0.
    inc = run({"samples_consumed": 80, "global_batch": 8,
               "realized_mixture": {"a": 0.67, "b": 0.33},
               "target_mixture": {"a": 0.666667, "b": 0.333333}})
    assert inc["samples_replayed"] == 0
    assert inc["samples_skipped"] == 0
    assert inc["mixture_drift"] == pytest.approx(0.003333, abs=1e-6)

    # The legacy epoch-replay resume shows its replays honestly.
    inc = run({"samples_consumed": 48, "global_batch": 8})
    assert inc["samples_replayed"] == 32
    assert inc["samples_skipped"] == 0
    assert "mixture_drift" not in inc

    # A cursor ahead of the optimizer step is a skip.
    inc = run({"samples_consumed": 96, "global_batch": 8})
    assert inc["samples_skipped"] == 16

    # No cursor fields -> pre-stream incident shape, unchanged.
    inc = run({})
    assert "samples_replayed" not in inc

    lines = "\n".join(render_recovery_lines(_recovery([
        {"kind": "run_start", "t": 100.0, "step": 0},
        {"kind": "span", "t": 105.0, "name": "step", "step": 12},
        {"kind": "run_start", "t": 120.0, "step": 10},
        {"kind": "resume", "t": 121.0, "step": 10, "restarts": 1,
         "samples_consumed": 80, "global_batch": 8},
    ])))
    assert "0 sample(s) replayed / 0 skipped" in lines


def test_recovery_counts_recorded_data_skips():
    """Deliberate skip-and-record corrupt-sample skips surface in the
    recovery section (with their (source, sample_id) evidence) even
    when the run never restarted."""
    from distributed_training_tpu.telemetry.summarize import (
        _recovery, render_recovery_lines)
    rec = _recovery([
        {"kind": "run_start", "t": 100.0, "step": 0},
        {"kind": "data_skip", "t": 101.0, "source": "wiki",
         "sample_id": 7, "step": 3},
    ])
    assert rec is not None and rec["restarts"] == 0
    assert rec["data_skips"] == [
        {"source": "wiki", "sample_id": 7, "step": 3}]
    text = "\n".join(render_recovery_lines(rec))
    assert "1 corrupt sample(s) skipped" in text
    assert "wiki[7]" in text


# -- e2e: crash → supervised restart → resume → identical result ----------


def _train_overrides(out_dir, snap, **extra):
    over = {
        "run.output_dir": out_dir,
        "train.snapshot_path": snap,
        "train.total_epochs": 4,
        "train.dataset_size": 32,
        "train.batch_size": 4,
        "train.log_every": 0,
        "train.save_every": 1,
    }
    over.update(extra)
    return [f"{k}={v}" for k, v in over.items()]


def test_supervised_crash_restart_resume_e2e(tmp_path):
    """The acceptance loop, end to end on CPU: `crash@20` under
    `--supervise` kills incarnation 0 mid-epoch-2; the supervisor
    restarts, the run resumes from the last good checkpoint (step 16)
    and completes all 4 epochs with final state IDENTICAL to an
    uninterrupted run. ~40s: three ~12s python+jax subprocesses."""
    from distributed_training_tpu.checkpoint.export import (
        restore_step_local)
    from distributed_training_tpu.telemetry.summarize import (
        summarize_run)

    faulty = tmp_path / "faulty"
    rc = launch_local_mod.main([
        "--nproc", "1", "--devices-per-proc", "1",
        "--log-dir", str(faulty / "logs"),
        "--supervise", "--max-restarts", "2",
        "--backoff-base-s", "0.05",
        "--ckpt-dir", str(faulty / "ckpt"),
        "--", "-m", "distributed_training_tpu.train",
        *_train_overrides(str(faulty / "out"), str(faulty / "ckpt")),
        "train.fault_plan=crash@20",
    ])
    assert rc == 0, "supervised run did not recover"

    # The supervisor saw exactly one crash and restarted once.
    sup_events = _read_jsonl(
        str(faulty / "logs" / "supervisor" / "events.jsonl"))
    restarts = [e for e in sup_events if e["kind"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["outcome"] == sup.CRASH
    assert restarts[0]["ckpt_step"] == 16 and restarts[0]["advanced"]

    # The run's own stream: fault fired once, resume from step 16.
    run_dir = str(faulty / "out" / "default")
    events = _read_jsonl(os.path.join(run_dir, "events.jsonl"))
    fired = [e for e in events if e["kind"] == "fault_injected"]
    assert [e["fault"] for e in fired] == ["crash@20"]
    resumes = [e for e in events if e["kind"] == "resume"]
    assert len(resumes) == 1
    assert resumes[0]["step"] == 16 and resumes[0]["restarts"] == 1

    # Summarizer recovery table: 1 restart, 4 steps lost (17..20).
    rec = summarize_run(run_dir)["recovery"]
    assert rec["restarts"] == 1
    assert rec["incidents"][0]["resumed_at_step"] == 16
    assert rec["incidents"][0]["steps_lost"] == 4

    # Uninterrupted reference run with the same config and seed.
    clean = tmp_path / "clean"
    procs = launch_local_mod.launch_local(
        ["-m", "distributed_training_tpu.train",
         *_train_overrides(str(clean / "out"), str(clean / "ckpt"))],
        num_processes=1, devices_per_process=1,
        log_dir=str(clean / "logs"))
    assert launch_local_mod.wait(procs, timeout=180) == 0

    got, got_step = restore_step_local(str(faulty / "ckpt"))
    want, want_step = restore_step_local(str(clean / "ckpt"))
    assert got_step == want_step == 32
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        got["params"], want["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        got["opt_state"], want["opt_state"])


def test_restart_incarnation_without_checkpoint_appends_stream(
        tmp_path):
    """A supervised restart that finds NO checkpoint (the crash
    predated the first save) must APPEND to the run's event stream —
    truncating would destroy the crashed segment's evidence — and
    must still emit a step-0 resume event for the recovery table."""
    out = tmp_path / "out"
    run_dir = out / "default"
    run_dir.mkdir(parents=True)
    marker = {"kind": "run_start", "t": 1.0, "step": 0,
              "crashed_segment_marker": True}
    with open(run_dir / "events.jsonl", "w") as f:
        f.write(json.dumps(marker) + "\n")
    procs = launch_local_mod.launch_local(
        ["-m", "distributed_training_tpu.train",
         *_train_overrides(str(out), str(tmp_path / "ckpt"))],
        num_processes=1, devices_per_process=1,
        log_dir=str(tmp_path / "logs"),
        env={sup.ENV_RESTART_COUNT: "1"})
    assert launch_local_mod.wait(procs, timeout=180) == 0
    events = _read_jsonl(str(run_dir / "events.jsonl"))
    assert events[0].get("crashed_segment_marker"), \
        "restart incarnation truncated the event stream"
    resumes = [e for e in events if e["kind"] == "resume"]
    assert len(resumes) == 1
    assert resumes[0]["step"] == 0 and resumes[0]["restarts"] == 1


def test_supervised_crash_loop_gives_up_e2e(tmp_path):
    """A fault that re-fires every restart must exhaust the budget and
    exit nonzero with the crashing child's rc — fast child (no jax),
    so this proves the launcher wiring in ~2s."""
    rc = launch_local_mod.main([
        "--nproc", "1",
        "--log-dir", str(tmp_path / "logs"),
        "--supervise", "--max-restarts", "1",
        "--backoff-base-s", "0.01",
        "--", "-c", "import sys; sys.exit(7)",
    ])
    assert rc == 7
    sup_events = _read_jsonl(
        str(tmp_path / "logs" / "supervisor" / "events.jsonl"))
    kinds = [e["kind"] for e in sup_events]
    assert kinds.count("restart") == 1
    give_up = [e for e in sup_events
               if e["kind"] == "supervisor_give_up"]
    assert give_up and give_up[0]["incarnations"] == 2
