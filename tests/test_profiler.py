"""utils/profiler.py: server lifecycle idempotence and the bounded
step-window trace on a stub trainer (no accelerator needed — the CPU
backend produces real xplane artifacts)."""

import os

from distributed_training_tpu.utils import profiler


class _StubTrainer:
    """Counts train_step calls; no jax work beyond a tiny op so
    block_until_ready has something real to wait on."""

    def __init__(self):
        self.calls = 0

    def train_step(self, batch):
        import jax.numpy as jnp
        self.calls += 1
        return {"loss": jnp.asarray(float(batch))}


def test_trace_steps_returns_result_with_logdir(tmp_path):
    trainer = _StubTrainer()
    logdir = str(tmp_path / "prof")
    res = profiler.trace_steps(trainer, [1.0, 2.0, 3.0, 4.0], logdir,
                               warmup=2)
    assert res == profiler.TraceResult(steps=2, logdir=logdir)
    assert trainer.calls == 4  # warmup steps ran too
    found = []
    for _root, _dirs, files in os.walk(logdir):
        found += files
    assert found, "trace produced no artifacts"


def test_trace_steps_short_iterator_consumed_by_warmup(tmp_path):
    trainer = _StubTrainer()
    res = profiler.trace_steps(trainer, [1.0], str(tmp_path / "p"),
                               warmup=5)
    assert res.steps == 0
    assert trainer.calls == 1


def test_start_server_idempotent_and_stop(unused_tcp_port=None):
    # A second start_server must return the running server, not crash
    # on the held port; stop_server is safe to call twice.
    port = 19377
    s1 = profiler.start_server(port)
    try:
        s2 = profiler.start_server(port)
        assert s1 is s2
        # A different-port request while running: logged, same server.
        s3 = profiler.start_server(port + 1)
        assert s3 is s1
    finally:
        profiler.stop_server()
    profiler.stop_server()  # idempotent no-op
