"""Calibrated cost model + scheduled comms/compute overlap.

Four layers:
- calibration tables (calibration/table.py): round-trip, fingerprint
  tamper refusal, interpolation semantics (latency floor / piecewise /
  tail extrapolation), chip-slug normalization, and the
  fallback-to-nominal lookup contract (missing vs unusable, loud
  note either way);
- planner consumption (parallel/planner.py): per-kind nominal
  fallback table (v4 and v5e RANK DIFFERENTLY where their wires
  should), calibrated ranking determinism, per-kind pricing actually
  steering the winner, calibration provenance on committed plans,
  and --check catching calibration drift;
- overlap flag derivation (parallel/overlap.py): per-platform sets,
  combiner-threshold clamping, env application that never overrides
  an operator's explicit setting, Plan.xla_overlap_flags and the
  stdlib plan-doc path agreeing, the launcher's cmd-scan application;
- the committed artifacts: conf/calibration/cpu.json matches the
  multichip_8dev_cpu plan's recorded fingerprint, the nominal-scored
  v5e plan says so, the planned audit target carries the overlap
  compiler options, and MULTICHIP_r07.json embeds calibration + flag
  provenance with a measured improvement over r06.
"""

import dataclasses
import json
import os

import pytest

from distributed_training_tpu.calibration import (CalibrationError,
                                                  CalibrationTable,
                                                  chip_slug,
                                                  load_table,
                                                  lookup_for_chip,
                                                  save_table)
from distributed_training_tpu.parallel import overlap, planner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CPU_TABLE_PATH = os.path.join(REPO, "conf", "calibration", "cpu.json")


def _pts(rate, latency):
    return [[b, latency + b / rate] for b in (1e4, 1e6, 1e8)]


def _table(device_kind="cpu", ag_rate=1e9):
    return CalibrationTable(
        device_kind=device_kind, platform="cpu", n_devices=8,
        collectives={
            "all-gather": _pts(ag_rate, 1e-4),
            "reduce-scatter": _pts(2e9, 1e-4),
            "all-reduce": _pts(1e10, 5e-5),
            "ppermute": _pts(1e9, 1e-4),
        },
        matmul=[[1e6, 5e10], [1e9, 1e11], [1e12, 1.4e11]],
        meta={"synthetic": True})


# ---------------------------------------------------------------------------
# Table artifact
# ---------------------------------------------------------------------------


def test_table_round_trip(tmp_path):
    t = _table()
    path = str(tmp_path / "cpu.json")
    save_table(t, path)
    loaded = load_table(path)
    assert loaded.fingerprint() == t.fingerprint()
    assert loaded.to_doc() == json.loads(json.dumps(t.to_doc()))


def test_table_tamper_refusal(tmp_path):
    """A hand-edited point (or curve) must refuse to load: every plan
    scored from the table inherits its numbers."""
    doc = _table().to_doc()
    doc["collectives"]["all-gather"][0][1] *= 10  # forge a latency
    p = tmp_path / "cpu.json"
    p.write_text(json.dumps(doc))
    with pytest.raises(CalibrationError, match="fingerprint"):
        load_table(str(p))


def test_table_interpolation_semantics():
    t = _table()
    pts = t.collectives["all-gather"]
    # Below the smallest measured point: the latency floor, not a
    # linear-through-zero fantasy.
    assert t.collective_seconds("all-gather", 1.0) == pts[0][1]
    # At a measured point: exactly that measurement.
    assert t.collective_seconds("all-gather", 1e6) == \
        pytest.approx(pts[1][1])
    # Between points: strictly between their times.
    mid = t.collective_seconds("all-gather", 5e5)
    assert pts[0][1] < mid < pts[1][1]
    # Above the largest: tail-bandwidth extrapolation keeps growing.
    assert t.collective_seconds("all-gather", 1e9) > pts[2][1]
    # Unknown kind is a loud error, not a silent zero-cost collective.
    with pytest.raises(CalibrationError, match="no curve"):
        t.collective_seconds("all-to-all", 1e6)
    # Matmul curve clamps at both ends (achievable FLOPs saturate).
    assert t.achievable_flops_per_s(1.0) == t.matmul[0][1]
    assert t.achievable_flops_per_s(1e15) == t.matmul[-1][1]
    lo, hi = t.matmul[0][1], t.matmul[1][1]
    assert lo < t.achievable_flops_per_s(5e8) < hi


def test_chip_slug_normalization():
    assert chip_slug("TPU v5 lite") == "v5e"
    assert chip_slug("v5e") == "v5e"
    assert chip_slug("TPU v4") == "v4"
    assert chip_slug("cpu") == "cpu"
    assert chip_slug("Banana 9000") == "banana_9000"


def test_lookup_fallback_contract(tmp_path):
    """Missing table -> nominal, status 'missing'; unusable
    (tampered) table -> nominal, status 'unusable' with a LOUD
    falling-back note; good table -> status 'measured' with its
    fingerprint in the note. Status is the structured signal
    consumers branch on — the prose note is free to be reworded."""
    calib_dir = str(tmp_path)
    lk = lookup_for_chip("v5e", calib_dir)
    assert lk.table is None and lk.status == "missing"
    assert "no committed calibration table" in lk.note

    t = _table(device_kind="v5e")
    save_table(t, os.path.join(calib_dir, "v5e.json"))
    lk = lookup_for_chip("v5e", calib_dir)
    assert lk.table is not None and lk.status == "measured"
    assert t.fingerprint() in lk.note

    doc = t.to_doc()
    doc["matmul"][0][1] *= 2  # tamper
    with open(os.path.join(calib_dir, "v5e.json"), "w") as f:
        json.dump(doc, f)
    lk = lookup_for_chip("v5e", calib_dir)
    assert lk.table is None and lk.status == "unusable"
    assert "FALLING BACK" in lk.note

    # Structurally malformed docs (missing keys, wrong point shapes)
    # must also land in the loud fallback, never a planner-bricking
    # traceback.
    for bad in ({"schema": 1},
                {**t.to_doc(), "collectives": {"all-gather": 5}},
                {k: v for k, v in t.to_doc().items()
                 if k != "matmul"}):
        with open(os.path.join(calib_dir, "v5e.json"), "w") as f:
            json.dump(bad, f)
        lk = lookup_for_chip("v5e", calib_dir)
        assert lk.table is None and lk.status == "unusable", bad


# ---------------------------------------------------------------------------
# Planner consumption
# ---------------------------------------------------------------------------


def _ranking_target(chip, **over):
    kw = dict(
        name="t", devices=8,
        model_kwargs=dict(vocab_size=256, d_model=128, n_heads=8,
                          n_kv_heads=4, n_layers=2, max_seq_len=256,
                          attention_impl="ring", attention_window=248,
                          dtype="float32", param_dtype="float32"),
        seq_len=256, chip=chip, hbm_gib=16.0,
        batch_candidates=(4, 8))
    kw.update(over)
    return planner.PlanTarget(**kw)


def test_nominal_table_is_per_kind():
    assert planner.nominal_ici_bytes_per_s("v4") == 3.0e11
    assert planner.nominal_ici_bytes_per_s("TPU v5 lite") == 2.0e11
    assert planner.nominal_ici_bytes_per_s("v5e") == 2.0e11
    # Unknown kinds keep the historical one-size constant.
    assert (planner.nominal_ici_bytes_per_s("banana")
            == planner.ICI_BYTES_PER_S)


def test_v4_and_v5e_rank_differently_where_they_should():
    """The satellite fix pinned: one nominal bandwidth used to make
    every chip rank identically. v4's faster wires (3e11 vs 2e11
    B/s) keep a comms-capped fsdp candidate competitive that v5e's
    roofline demotes — the two chips must produce different orders
    over the SAME candidate set."""
    v4 = [c.key for c, _s in planner.rank_candidates(
        _ranking_target("v4"), calib=None)]
    v5e = [c.key for c, _s in planner.rank_candidates(
        _ranking_target("v5e"), calib=None)]
    assert sorted(v4) == sorted(v5e)  # same candidates...
    assert v4 != v5e                  # ...different order
    # And the comms half prices exactly by the nominal ratio.
    cand = planner.Candidate(1, 1, 8, 1, 1, "none", 8)
    n_params = planner._n_params(_ranking_target("v4"))
    s4 = planner.score_candidate(_ranking_target("v4"), cand,
                                 n_params, calib=None)
    s5 = planner.score_candidate(_ranking_target("v5e"), cand,
                                 n_params, calib=None)
    assert s4["comms_s"] == pytest.approx(
        s5["comms_s"] * 2.0e11 / 3.0e11)


def test_calibrated_ranking_is_deterministic():
    t = _ranking_target("cpu")
    calib = _table()
    a = [(c.key, s["score"])
         for c, s in planner.rank_candidates(t, calib=calib)]
    b = [(c.key, s["score"])
         for c, s in planner.rank_candidates(t, calib=calib)]
    assert a == b and a
    # The calibrated flag rides every record, honestly.
    ranked = planner.rank_candidates(t, calib=calib)
    assert all(s["calibrated"] for _c, s in ranked)
    assert planner.rank_candidates(t, calib=None)[0][1][
        "calibrated"] is False


def test_per_kind_pricing_steers_the_winner():
    """A measured curve that says THIS interconnect all-gathers
    terribly must demote fsdp (all-gather + reduce-scatter traffic)
    below pure dp (all-reduce traffic) — per-kind pricing is the
    point of calibrating per collective."""
    t = _ranking_target("cpu", batch_candidates=(8,),
                        remat_candidates=("none",))
    fair = _table()
    slow_ag = _table(ag_rate=1e5)  # all-gather 10,000x slower
    top_fair = [c.key for c, _s in
                planner.rank_candidates(t, calib=fair)]
    top_slow = [c.key for c, _s in
                planner.rank_candidates(t, calib=slow_ag)]
    fsdp8 = "pp1.dp1.fsdp8.sp1.tp1/none/b8"
    dp8 = "pp1.dp8.fsdp1.sp1.tp1/none/b8"
    # Equal-cost curves keep the historical tie-break (fsdp first)...
    assert top_fair.index(fsdp8) < top_fair.index(dp8)
    # ...a slow all-gather flips it.
    assert top_slow.index(dp8) < top_slow.index(fsdp8)


def test_committed_cpu_table_is_sane():
    """Physical sanity on the committed measurement: every curve is
    (noise-tolerantly) non-decreasing in bytes, and all-reduce at
    the largest accounted size costs within 3x of reduce-scatter —
    the misaccounting this pins (a sharded psum operand timing 1/n
    of the tensor) made all-reduce ~10x cheaper than its ring
    phases' parts."""
    t = load_table(CPU_TABLE_PATH)
    for kind, pts in t.collectives.items():
        for (b0, t0), (b1, t1) in zip(pts, pts[1:]):
            assert t1 >= t0 * 0.8, (kind, pts)
    top = t.collectives["reduce-scatter"][-1][0]
    ar = t.collective_seconds("all-reduce", top)
    rs = t.collective_seconds("reduce-scatter", top)
    assert rs / 3 <= ar <= rs * 3, (ar, rs)


def test_committed_cpu_plan_matches_committed_table():
    """The calibrated-cost-model path as committed: the
    multichip_8dev_cpu plan records source=measured with the EXACT
    fingerprint of conf/calibration/cpu.json, and check_plan (the
    tier-1 planner gate's unit) passes."""
    plan = planner.load_plan("multichip_8dev_cpu")
    cal = plan.provenance["calibration"]
    assert cal["source"] == "measured"
    assert cal["fingerprint"] == load_table(
        CPU_TABLE_PATH).fingerprint()
    assert planner.check_plan(
        planner.PLAN_TARGETS["multichip_8dev_cpu"]) == []


def test_committed_v5e_plan_records_nominal_fallback():
    """No v5e table is committed: the multichip_8dev plan must SAY
    its scores are nominal (and which constants were used), not
    pretend to be measured."""
    plan = planner.load_plan("multichip_8dev")
    cal = plan.provenance["calibration"]
    assert cal["source"] == "nominal"
    assert cal["fingerprint"] is None
    assert cal["nominal_ici_bytes_per_s"] == 2.0e11
    assert "no committed calibration table" in cal["note"]


def test_check_plan_catches_calibration_drift(monkeypatch):
    """Re-measuring a chip (new table fingerprint) — or losing the
    table — without re-planning must fail --check, BEFORE the
    generic ranking-drift message: the operator should be told the
    calibration moved, not left diffing candidate lists."""
    from distributed_training_tpu.calibration import CalibrationLookup
    target = planner.PLAN_TARGETS["multichip_8dev_cpu"]
    # Table vanished / unusable -> nominal != recorded measured.
    monkeypatch.setattr(
        planner, "resolve_calibration",
        lambda _t: CalibrationLookup(
            None, "no committed calibration table (test)", "missing"))
    problems = planner.check_plan(target)
    assert problems and "calibration drift" in problems[0]
    # A DIFFERENT measurement -> fingerprint mismatch.
    other = _table()
    monkeypatch.setattr(
        planner, "resolve_calibration",
        lambda _t: CalibrationLookup(other, "calibrated (test)",
                                     "measured"))
    problems = planner.check_plan(target)
    assert problems and "calibration drift" in problems[0]
    # An UNUSABLE committed table is repo damage: --check goes red
    # even though plan_search would proceed on nominal constants
    # (and even for a nominal-scored plan, where the fingerprint
    # comparison alone would see None == None).
    monkeypatch.setattr(
        planner, "resolve_calibration",
        lambda _t: CalibrationLookup(
            None, "committed calibration table x is unusable "
            "(test); FALLING BACK", "unusable"))
    problems = planner.check_plan(target)
    assert problems and "unusable" in problems[0]


# ---------------------------------------------------------------------------
# Overlap flag derivation + application
# ---------------------------------------------------------------------------


def test_overlap_flags_per_platform():
    cpu = overlap.flags_for("cpu")
    assert cpu["xla_cpu_enable_concurrency_optimized_scheduler"] \
        is True
    tpu = overlap.flags_for("tpu")
    assert tpu["xla_tpu_enable_latency_hiding_scheduler"] is True
    gpu = overlap.flags_for("gpu", collective_bytes_per_step=891208)
    assert gpu["xla_gpu_enable_latency_hiding_scheduler"] is True
    # Combiner thresholds derived from the plan's measured bytes.
    assert gpu["xla_gpu_all_gather_combine_threshold_bytes"] == 1 << 20
    assert overlap.flags_for("banana") == {}
    # An unsharded mesh compiles zero collectives: nothing to hide.
    assert overlap.flags_for(
        "cpu", mesh={"dp": 1, "fsdp": 1, "tp": 1}) == {}


def test_combine_threshold_clamps():
    assert overlap.combine_threshold_bytes(None) == 1 << 20
    assert overlap.combine_threshold_bytes(0) == 1 << 20
    assert overlap.combine_threshold_bytes(5 << 20) == 8 << 20
    assert overlap.combine_threshold_bytes(1 << 30) == 1 << 26


def test_render_and_apply_to_env():
    flags = {"xla_cpu_enable_concurrency_optimized_scheduler": True,
             "xla_gpu_all_gather_combine_threshold_bytes": 1 << 20}
    rendered = overlap.render_xla_flags(flags)
    assert ("--xla_cpu_enable_concurrency_optimized_scheduler=true"
            in rendered)
    assert ("--xla_gpu_all_gather_combine_threshold_bytes=1048576"
            in rendered)
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    applied = overlap.apply_to_env(flags, env)
    assert applied == sorted(flags)
    assert "--xla_force_host_platform_device_count=8" \
        in env["XLA_FLAGS"]
    # Idempotent: a second application is a no-op.
    assert overlap.apply_to_env(flags, env) == []
    # An operator's explicit setting (even =false) outranks the plan.
    env2 = {"XLA_FLAGS":
            "--xla_cpu_enable_concurrency_optimized_scheduler=false"}
    applied2 = overlap.apply_to_env(
        {"xla_cpu_enable_concurrency_optimized_scheduler": True},
        env2)
    assert applied2 == []
    assert "=false" in env2["XLA_FLAGS"]
    assert overlap.active_in_env(flags, env)
    assert overlap.active_in_env(flags, {"XLA_FLAGS": ""}) == {}


def test_flag_names_tokenized_not_substring_matched():
    """A longer-named flag in the env must not shadow a shorter one
    that is its prefix, and active_in_env must report the ENV's
    actual value, not the plan's derivation."""
    env = {"XLA_FLAGS": "--xla_tpu_enable_async_collective_fusion"
                        "_fuse_all_gather=false"}
    applied = overlap.apply_to_env(dict(overlap.TPU_OVERLAP_FLAGS),
                                   env)
    # The base fusion flag is NOT suppressed by its longer sibling...
    assert "xla_tpu_enable_async_collective_fusion" in applied
    # ...while the operator's explicit sub-flag stays untouched.
    assert "xla_tpu_enable_async_collective_fusion_fuse_all_gather" \
        not in applied
    assert env["XLA_FLAGS"].count(
        "_fuse_all_gather=false") == 1
    active = overlap.active_in_env(overlap.TPU_OVERLAP_FLAGS, env)
    # Provenance reports what actually ran: the env's =false, not
    # the plan's derived True.
    assert active[
        "xla_tpu_enable_async_collective_fusion_fuse_all_gather"] \
        is False
    assert active["xla_tpu_enable_async_collective_fusion"] is True
    # Repeated flag: XLA honors the LAST occurrence; so must
    # provenance.
    env3 = {"XLA_FLAGS": "--xla_gpu_all_reduce_combine_threshold_"
                         "bytes=1048576 --xla_gpu_all_reduce_"
                         "combine_threshold_bytes=67108864"}
    assert overlap.active_in_env(
        {"xla_gpu_all_reduce_combine_threshold_bytes": 1 << 20},
        env3) == {"xla_gpu_all_reduce_combine_threshold_bytes":
                  67108864}


def test_plan_surface_and_doc_path_agree():
    """Plan.xla_overlap_flags (the API surface) and the stdlib
    flags_for_plan_doc (launcher/targets path) must derive the same
    set — two derivations would drift."""
    plan = planner.load_plan("multichip_8dev")
    with open(planner.plan_path("multichip_8dev"),
              encoding="utf-8") as f:
        doc = json.load(f)
    for platform in ("cpu", "tpu", "gpu"):
        assert plan.xla_overlap_flags(platform) == \
            overlap.flags_for_plan_doc(doc, platform)
    assert plan.xla_overlap_flags("cpu")  # non-empty: fsdp8 mesh
    # An unsharded plan derives nothing.
    single = dataclasses.replace(
        plan, mesh={a: 1 for a in planner.MESH_AXES})
    assert single.xla_overlap_flags("cpu") == {}


def test_launcher_applies_overlap_flags_from_cmd(monkeypatch):
    """launch.local scans the train command for a pinned plan and
    pre-applies its flags to the (inherited) child XLA_FLAGS; an
    explicit train.xla_overlap_flags=false in the command wins."""
    from distributed_training_tpu.launch import local
    monkeypatch.setenv("XLA_FLAGS", "")
    applied = local.apply_overlap_flags_from_cmd(
        ["-m", "distributed_training_tpu.train",
         "train.sharding_plan=multichip_8dev"])
    assert applied == [
        "xla_cpu_enable_concurrency_optimized_scheduler"]
    assert ("xla_cpu_enable_concurrency_optimized_scheduler"
            in os.environ["XLA_FLAGS"])
    # Every spelling the child's yaml config layer reads as False
    # must disable the launcher too.
    for tok in ("false", "False", "off", "no", "0"):
        monkeypatch.setenv("XLA_FLAGS", "")
        assert local.apply_overlap_flags_from_cmd(
            ["train.sharding_plan=multichip_8dev",
             f"train.xla_overlap_flags={tok}"]) == [], tok
    # Repeated overrides: LAST wins, matching the child's config
    # layer — false-then-true applies, true-then-false does not.
    monkeypatch.setenv("XLA_FLAGS", "")
    assert local.apply_overlap_flags_from_cmd(
        ["train.sharding_plan=multichip_8dev",
         "train.xla_overlap_flags=false",
         "train.xla_overlap_flags=true"]) != []
    monkeypatch.setenv("XLA_FLAGS", "")
    assert local.apply_overlap_flags_from_cmd(
        ["train.sharding_plan=multichip_8dev",
         "train.xla_overlap_flags=true",
         "train.xla_overlap_flags=false"]) == []
    assert local.apply_overlap_flags_from_cmd(["-m", "x"]) == []
    # A bad plan reference stays the child's loud failure.
    assert local.apply_overlap_flags_from_cmd(
        ["train.sharding_plan=no_such_plan"]) == []


def test_planned_audit_target_carries_overlap_options():
    """The overlap ratchet must score the schedule the flagged
    consumers run: the planned target's compile options are exactly
    the plan's cpu flag set."""
    from distributed_training_tpu.analysis import targets
    t = targets.TARGETS["multichip_r06_planned"]
    plan = planner.load_plan("multichip_8dev")
    assert dict(t.compiler_options) == plan.xla_overlap_flags("cpu")
    assert t.min_overlap == 0.85


# ---------------------------------------------------------------------------
# Committed ledger artifacts
# ---------------------------------------------------------------------------


def test_multichip_r07_entry_provenance():
    """The acceptance artifact: r07 measured on the same 8-device
    {fsdp: 8} mesh as r06, faster, reshard-clean, with calibration
    AND scheduler-flag provenance embedded."""
    with open(os.path.join(REPO, "MULTICHIP_r07.json"),
              encoding="utf-8") as f:
        r07 = json.load(f)
    with open(os.path.join(REPO, "MULTICHIP_r06.json"),
              encoding="utf-8") as f:
        r06 = json.load(f)
    assert r07["dryrun"] is False
    assert r07["mesh"] == r06["mesh"] == {"fsdp": 8}
    assert r07["n_devices"] == 8
    assert r07["spmd_reshard_warnings"] == 0
    assert r07["step_time_ms"] < r06["step_time_ms"]
    assert r07["tokens_per_sec"] > r06["tokens_per_sec"]
    assert r07["compared_to"]["entry"] == "MULTICHIP_r06.json"
    assert r07["compared_to"]["step_time_speedup"] > 1.0
    # Calibration provenance: measured, matching the committed table.
    assert r07["calibration"]["source"] == "measured"
    assert r07["calibration"]["fingerprint"] == load_table(
        CPU_TABLE_PATH).fingerprint()
    # Scheduler provenance: the overlap flags were derived AND active.
    fl = r07["xla_overlap_flags"]
    assert fl["enabled"] is True
    assert fl["active"] == fl["derived"] != {}
    for name in fl["derived"]:
        assert name in fl["xla_flags_env"]
