"""Tensor-parallel and expert-parallel end-to-end coverage: the same
model/batch/seed must produce the same loss trajectory under every
layout (DDP reference vs TP vs EP-sharded MoE)."""

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticLMDataset)
from distributed_training_tpu.models.transformer import (
    Transformer, TransformerConfig)
from distributed_training_tpu.parallel import get_strategy
from distributed_training_tpu.runtime import fake_cpu_runtime
from distributed_training_tpu.train.trainer import Trainer


def run_losses(rt, strategy, model_kwargs=None, steps=3):
    cfg = Config()
    cfg.train.batch_size = 2
    cfg.train.total_epochs = 1
    cfg.train.log_every = 0
    cfg.train.learning_rate = 0.01
    cfg.train.parallel_strategy = strategy
    cfg.train.min_shard_elems = 1
    mk = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
              max_seq_len=16, dtype="float32")
    mk.update(model_kwargs or {})
    model = Transformer(TransformerConfig(**mk))
    ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64, seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
    trainer = Trainer(cfg, rt, model, loader)
    return ([float(trainer.train_step(b)["loss"])
             for b in loader.epoch(0)][:steps], trainer)


def test_tp_matches_ddp_losses():
    """mesh (dp=2, tp=4) with Megatron-style sharding == plain dp=2."""
    ddp_losses, _ = run_losses(fake_cpu_runtime(2), "ddp")
    tp_losses, trainer = run_losses(fake_cpu_runtime(8, tp=4), "tp")
    np.testing.assert_allclose(ddp_losses, tp_losses, rtol=1e-5,
                               atol=1e-6)
    # and TP actually sharded something over 'tp'
    specs = jax.tree.leaves(
        trainer.strategy.specs_for_tree(
            jax.eval_shape(trainer.model.init, trainer.init_rng),
            trainer.model.logical_axes()),
        is_leaf=lambda x: True)
    assert any("tp" in str(s) for s in specs)


def test_ep_moe_matches_ddp_losses():
    """MoE experts sharded over the fsdp axis (expert parallelism) == the
    same MoE replicated under ddp."""
    mk = dict(moe_num_experts=4, moe_top_k=2)
    # both meshes expose 8 data shards (dp=8 vs dp=2 x fsdp=4) so the
    # global batches are identical and only the layout differs
    ddp_losses, _ = run_losses(fake_cpu_runtime(8), "ddp",
                               model_kwargs=mk)
    ep_losses, trainer = run_losses(fake_cpu_runtime(8, fsdp=4), "fsdp",
                                    model_kwargs=mk)
    np.testing.assert_allclose(ddp_losses, ep_losses, rtol=1e-5,
                               atol=1e-6)
    # expert dim is sharded: the wi (L, E, D, F) leaf routes E -> fsdp
    specs = trainer.strategy.specs_for_tree(
        jax.eval_shape(trainer.model.init, trainer.init_rng),
        trainer.model.logical_axes())
    assert "fsdp" in str(specs["mlp"]["wi"])


def test_tp_with_gqa_kv_heads():
    """kv-head sharding under TP requires n_kv_heads % tp == 0; with
    n_kv_heads=2 and tp=2 it shards, with tp=4 it prunes to replicated
    instead of crashing."""
    strat2 = get_strategy("tp", fake_cpu_runtime(8, tp=2).spec,
                          min_shard_elems=1)
    spec = strat2.param_spec((2, 32, 2, 8), (None, "embed", "kv", None))
    assert "tp" in str(spec)
    strat4 = get_strategy("tp", fake_cpu_runtime(8, tp=4).spec,
                          min_shard_elems=1)
    spec = strat4.param_spec((2, 32, 2, 8), (None, "embed", "kv", None))
    assert "tp" not in str(spec)


@pytest.mark.parametrize("strategy,axes", [("fsdp", {"fsdp": 8}),
                                           ("tp", {"tp": 2, "fsdp": 2})])
def test_moe_trains_under_layouts(strategy, axes):
    rt = fake_cpu_runtime(8, **axes)
    losses, _ = run_losses(rt, strategy,
                           model_kwargs=dict(moe_num_experts=4))
    assert all(np.isfinite(x) for x in losses)
