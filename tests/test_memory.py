"""HBM estimator: exact param accounting vs real models, sane
activation scaling, and the fit/sharding arithmetic."""

import jax
import numpy as np
import pytest

from distributed_training_tpu.models.transformer import (Transformer,
                                                         TransformerConfig)
from distributed_training_tpu.utils import memory


def cfg(**kw):
    base = dict(vocab_size=512, d_model=64, n_layers=3, n_heads=4,
                max_seq_len=64, dtype="bfloat16", param_dtype="float32")
    base.update(kw)
    return TransformerConfig(**base)


def test_param_count_matches_real_model():
    c = cfg()
    model = Transformer(c)
    params = model.init(jax.random.PRNGKey(0))
    real = memory.param_count(params)
    est = memory.estimate_transformer_memory(c, 1, 64)
    est_params = est.params_gib * 1024 ** 3 / 4  # fp32 → count
    assert est_params == pytest.approx(real, rel=0.01)


def test_remat_reduces_activations():
    ests = {
        name: memory.estimate_transformer_memory(
            cfg(remat=remat, remat_policy=pol), 8, 64).activations_gib
        for name, remat, pol in (
            ("none", False, "full"),
            ("selective", True, "selective"),
            ("full", True, "full"))
    }
    assert ests["none"] > ests["selective"] > ests["full"]


def test_sharding_divides_state():
    c = cfg()
    one = memory.estimate_transformer_memory(c, 8, 64, fsdp=1)
    eight = memory.estimate_transformer_memory(c, 8, 64, fsdp=8)
    assert eight.params_gib == pytest.approx(one.params_gib / 8)
    assert eight.opt_gib == pytest.approx(one.opt_gib / 8)


def test_activations_scale_with_batch():
    c = cfg()
    a = memory.estimate_transformer_memory(c, 4, 64).activations_gib
    b = memory.estimate_transformer_memory(c, 8, 64).activations_gib
    assert b == pytest.approx(2 * a, rel=1e-6)


def test_fits_and_unknown_kind():
    c = cfg()
    est = memory.estimate_transformer_memory(c, 1, 64)
    assert est.fits("v5e")  # tiny model, 16 GiB chip
    with pytest.raises(ValueError, match="device kind"):
        est.fits("h100")


def test_7b_needs_sharding():
    """The BASELINE 7B config cannot fit one v5e unsharded but fits
    per-chip on a 32-way FSDP pod — the arithmetic the launcher docs
    quote."""
    from distributed_training_tpu.models.transformer import PRESETS
    c = TransformerConfig(**PRESETS["transformer_7b"])  # preset has remat
    alone = memory.estimate_transformer_memory(c, 1, 2048, fsdp=1)
    assert not alone.fits("v5e")
    sharded = memory.estimate_transformer_memory(c, 1, 2048, fsdp=32)
    assert sharded.params_gib + sharded.opt_gib < alone.params_gib + \
        alone.opt_gib
