"""HBM estimator: exact param accounting vs real models, sane
activation scaling, and the fit/sharding arithmetic."""

import os

import jax
import pytest

from distributed_training_tpu.models.transformer import (Transformer,
                                                         TransformerConfig)
from distributed_training_tpu.utils import memory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cfg(**kw):
    base = dict(vocab_size=512, d_model=64, n_layers=3, n_heads=4,
                max_seq_len=64, dtype="bfloat16", param_dtype="float32")
    base.update(kw)
    return TransformerConfig(**base)


def test_param_count_matches_real_model():
    c = cfg()
    model = Transformer(c)
    params = model.init(jax.random.PRNGKey(0))
    real = memory.param_count(params)
    est = memory.estimate_transformer_memory(c, 1, 64)
    est_params = est.params_gib * 1024 ** 3 / 4  # fp32 → count
    assert est_params == pytest.approx(real, rel=0.01)


def test_remat_reduces_activations():
    ests = {
        name: memory.estimate_transformer_memory(
            cfg(remat=remat, remat_policy=pol), 8, 64).activations_gib
        for name, remat, pol in (
            ("none", False, "full"),
            ("mlp", True, "mlp"),
            ("selective", True, "selective"),
            ("full", True, "full"))
    }
    assert (ests["none"] > ests["mlp"] > ests["selective"]
            > ests["full"])


def test_sharding_divides_state():
    c = cfg()
    one = memory.estimate_transformer_memory(c, 8, 64, fsdp=1)
    eight = memory.estimate_transformer_memory(c, 8, 64, fsdp=8)
    assert eight.params_gib == pytest.approx(one.params_gib / 8)
    assert eight.opt_gib == pytest.approx(one.opt_gib / 8)


def test_activations_scale_with_batch():
    """Dense loss head: activations scale linearly with batch. Fused
    head: the per-chunk logits tile is a CONSTANT (that's the point),
    so scaling is affine — the batch-dependent part still doubles."""
    cd = cfg(loss_impl="dense")
    a = memory.estimate_transformer_memory(cd, 4, 64).activations_gib
    b = memory.estimate_transformer_memory(cd, 8, 64).activations_gib
    assert b == pytest.approx(2 * a, rel=1e-6)

    cf = cfg()  # fused default
    f0 = memory.estimate_transformer_memory(cf, 1, 64).activations_gib
    f4 = memory.estimate_transformer_memory(cf, 4, 64).activations_gib
    f8 = memory.estimate_transformer_memory(cf, 8, 64).activations_gib
    # affine in batch: f(b) = const + b * slope
    assert f8 - f4 == pytest.approx((f4 - f0) * 4 / 3, rel=1e-6)
    # fused beats dense once the token count exceeds the chunk tile
    # (B·S > chunk_rows; at tiny batches the constant tile dominates)
    big_d = memory.estimate_transformer_memory(
        cfg(loss_impl="dense"), 64, 64).activations_gib
    big_f = memory.estimate_transformer_memory(cfg(), 64, 64) \
        .activations_gib
    assert big_f < big_d


def test_fits_and_unknown_kind():
    c = cfg()
    est = memory.estimate_transformer_memory(c, 1, 64)
    assert est.fits("v5e")  # tiny model, 16 GiB chip
    with pytest.raises(ValueError, match="device kind"):
        est.fits("h100")


def test_7b_needs_sharding():
    """The BASELINE 7B config cannot fit one v5e unsharded but fits
    per-chip on a 32-way FSDP pod — the arithmetic the launcher docs
    quote."""
    from distributed_training_tpu.models.transformer import PRESETS
    c = TransformerConfig(**PRESETS["transformer_7b"])  # preset has remat
    alone = memory.estimate_transformer_memory(c, 1, 2048, fsdp=1)
    assert not alone.fits("v5e")
    sharded = memory.estimate_transformer_memory(c, 1, 2048, fsdp=32)
    assert sharded.params_gib + sharded.opt_gib < alone.params_gib + \
        alone.opt_gib


def test_offload_does_not_hide_step_peak():
    """offload_opt must NOT claim HBM savings: the current trainer
    streams the whole moment tree back on-device for the compiled
    step, so the per-step peak fits() models still includes it. The
    path that genuinely shrinks moments is adafactor (factored second
    moment) — the 1B single-chip plan (benchmarks/plan_memory.py)."""
    from distributed_training_tpu.models.transformer import PRESETS
    c = TransformerConfig(remat=True, remat_policy="full",
                          **PRESETS["transformer_1b"])
    resident = memory.estimate_transformer_memory(
        c, 1, 1024, optimizer="adamw")
    offloaded = memory.estimate_transformer_memory(
        c, 1, 1024, optimizer="adamw", offload_opt=True)
    assert resident.opt_gib > 8  # 2 fp32 moments of ~1.3B params
    assert offloaded.opt_gib == resident.opt_gib
    assert not offloaded.fits("v5e")
    factored = memory.estimate_transformer_memory(
        c, 1, 1024, optimizer="adafactor")
    assert factored.opt_gib < 0.5
    assert factored.fits("v5e")


def test_plan_memory_all_plans_fit():
    """Every committed BASELINE memory plan must keep fitting its
    target chip — a regression guard on estimator recalibrations."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "plan_memory.py")],
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-500:]
    plans = [json.loads(line) for line in
             out.stdout.strip().splitlines()]
    assert len(plans) >= 5
    assert all(p["fits"] for p in plans)
    names = {p["plan"] for p in plans}
    assert "1b_single_chip_v5e" in names  # what bench_1b runs
    assert "7b_fsdp8_v4" in names        # BASELINE config 5 layout
