"""Multi-host observability: cross-host stream aggregation (clock
alignment, skew + straggler attribution, per-host goodput), the
runtime straggler detector, static collective-traffic accounting, and
the launch/local.py-driven 2-process CPU end-to-end (per-host streams
-> one merged summary)."""

import json
import os

import numpy as np
import pytest

from distributed_training_tpu import telemetry
from distributed_training_tpu.telemetry import aggregate
from distributed_training_tpu.telemetry.collectives import (
    audit_hlo_text, parse_replica_groups)
from distributed_training_tpu.telemetry.straggler import (
    StragglerDetector, flag_stragglers)


@pytest.fixture(autouse=True)
def _fresh_ambient():
    telemetry.uninstall()
    yield
    telemetry.uninstall()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()
                and not line.startswith("{torn")]


# The injected per-host clock offsets (seconds) and the slow host the
# aggregate/straggler tests must re-discover from the streams alone.
OFFSETS = {0: 0.0, 1: 5.0, 2: -3.0, 3: 0.5}
SLOW_HOST = 3


def _pod_dir(tmp_path, offsets=None, steps=10, clock_sync=True):
    """Synthetic 4-host run dir: host_<i>/events.jsonl streams with
    injected clock offsets, one slow host (2.5x step, 12x data_wait),
    a fat checkpoint on host 0, a collectives event on the
    coordinator, and a torn trailing line (crashed-writer
    tolerance)."""
    run_dir = tmp_path / "pod"
    run_dir.mkdir()
    with open(run_dir / "metrics.jsonl", "w") as f:
        for i, loss in ((1, 2.0), (2, 1.5), (3, 1.0)):
            f.write(json.dumps({"step": i, "loss": loss}) + "\n")
    for h, off in (offsets if offsets is not None else OFFSETS).items():
        host_dir = run_dir / f"host_{h}"
        host_dir.mkdir()
        with open(host_dir / "events.jsonl", "w") as f:
            t0 = 1000.0 + off
            f.write(json.dumps({"kind": "run_start", "t": t0,
                                "step": 0, "host": h}) + "\n")
            if clock_sync:
                f.write(json.dumps(
                    {"kind": "clock_sync", "t": t0, "t_sync": t0,
                     "process_index": h, "process_count": 4,
                     "host": h}) + "\n")
            t = t0
            for s in range(1, steps + 1):
                wait = 0.12 if h == SLOW_HOST else 0.01
                dur = 0.25 if h == SLOW_HOST else 0.10
                t += wait
                f.write(json.dumps(
                    {"kind": "span", "name": "data_wait", "t": t,
                     "dur_s": wait, "depth": 0, "step": s,
                     "host": h}) + "\n")
                t += dur
                f.write(json.dumps(
                    {"kind": "span", "name": "step", "t": t,
                     "dur_s": dur, "depth": 0, "step": s,
                     "host": h}) + "\n")
            # Collective save: host 0 is slow to serialize, everyone
            # else burns the difference blocked at the barrier.
            ckpt = 0.30 if h == 0 else 0.05
            t += ckpt
            f.write(json.dumps(
                {"kind": "span", "name": "ckpt_save", "t": t,
                 "dur_s": ckpt, "depth": 0, "host": h}) + "\n")
            if h == 0:
                f.write(json.dumps(
                    {"kind": "collectives", "t": t, "host": h,
                     "schema": 1, "total_collectives": 2,
                     "bytes_per_step": 4096,
                     "by_kind": {"all-reduce":
                                 {"count": 2, "bytes": 4096}},
                     "by_axis": {"dp": {"count": 2, "bytes": 4096}},
                     "mesh": {"dp": 4}}) + "\n")
            f.write("{torn line\n")
    return run_dir


# -- clock alignment / merge ----------------------------------------------


def test_clock_offsets_recover_injected_skew(tmp_path):
    streams = aggregate.load_host_streams(str(_pod_dir(tmp_path)))
    offs = aggregate.clock_offsets(streams)
    # Offsets are relative to the median host; pairwise differences
    # must reproduce the injected skew exactly.
    for h in OFFSETS:
        assert offs[h] - offs[0] == pytest.approx(
            OFFSETS[h] - OFFSETS[0], abs=1e-9)


def test_merged_timeline_monotonic_and_host_tagged(tmp_path):
    streams = aggregate.load_host_streams(str(_pod_dir(tmp_path)))
    merged = aggregate.merge_streams(streams)
    ts = [r["t"] for r in merged]
    assert ts == sorted(ts)
    assert {r["host"] for r in merged} == set(OFFSETS)
    # After alignment all four run_starts collapse onto (nearly) the
    # same instant instead of spanning the 8s injected skew.
    starts = [r["t"] for r in merged if r["kind"] == "run_start"]
    assert max(starts) - min(starts) < 1e-6


def test_streams_without_clock_sync_merge_uncorrected(tmp_path):
    run_dir = _pod_dir(tmp_path, clock_sync=False)
    streams = aggregate.load_host_streams(str(run_dir))
    assert aggregate.clock_offsets(streams) == \
        {h: 0.0 for h in OFFSETS}
    merged = aggregate.merge_streams(streams)
    assert len(merged) == sum(len(s) for s in streams.values())


def test_unsynced_clock_record_gets_zero_correction(tmp_path):
    """A host whose setup barrier failed emits ``t_sync: null``
    (runtime.clock_sync_record with clock_sync_unix=None): the
    aggregator must NOT invent a clock offset from it — an unsynced
    wall-clock reading would be corrected by what is actually startup
    skew."""
    from distributed_training_tpu.runtime import fake_cpu_runtime

    rec = fake_cpu_runtime(8).clock_sync_record()
    assert rec["t_sync"] is None
    run_dir = _pod_dir(tmp_path)
    streams = aggregate.load_host_streams(str(run_dir))
    # Replace host 1's sync reading with the barrier-failed form.
    for e in streams[1]:
        if e.get("kind") == "clock_sync":
            e["t_sync"] = None
    offs = aggregate.clock_offsets(streams)
    assert offs[1] == 0.0
    # The synced hosts still align against their own median.
    assert offs[0] != 0.0 or offs[2] != 0.0


def test_write_merged_round_trips(tmp_path):
    run_dir = str(_pod_dir(tmp_path))
    out = os.path.join(run_dir, "merged.jsonl")
    n = aggregate.write_merged(run_dir, out)
    rows = _read_jsonl(out)
    assert len(rows) == n
    ts = [r["t"] for r in rows]
    assert ts == sorted(ts)


def test_is_multihost_run_dir(tmp_path):
    assert aggregate.is_multihost_run_dir(str(_pod_dir(tmp_path)))
    flat = tmp_path / "flat"
    flat.mkdir()
    (flat / "events.jsonl").write_text("")
    assert not aggregate.is_multihost_run_dir(str(flat))
    # host_<i> dir without a stream does not count either.
    empty = tmp_path / "empty"
    (empty / "host_0").mkdir(parents=True)
    assert not aggregate.is_multihost_run_dir(str(empty))


# -- skew / straggler attribution (the acceptance fixture) ----------------


def test_skew_report_attributes_slow_host(tmp_path):
    streams = aggregate.load_host_streams(str(_pod_dir(tmp_path)))
    skew = aggregate.skew_report(streams)
    assert skew["step_spread"]["worst_host"] == SLOW_HOST
    assert skew["step_spread"]["worst"]["slowest_host"] == SLOW_HOST
    assert skew["steps_compared"] == 10
    per = skew["per_host"]
    assert per[SLOW_HOST]["step"] == pytest.approx(0.25)
    assert per[0]["step"] == pytest.approx(0.10)
    assert per[SLOW_HOST]["data_wait_total_s"] == pytest.approx(1.2)
    # Host 0's 0.30s save vs everyone's 0.05s: the fast hosts waited.
    assert skew["ckpt_barrier_spread_s"] == pytest.approx(0.25)


def test_aggregate_run_flags_injected_straggler_and_goodput(tmp_path):
    summary = aggregate.aggregate_run(str(_pod_dir(tmp_path)))
    assert summary["multihost"] and summary["hosts"] == [0, 1, 2, 3]
    # The offline pass must attribute BOTH metrics to the slow host
    # and nothing to anyone else.
    offline = summary["stragglers"]["offline"]
    assert offline and {v["host"] for v in offline} == {SLOW_HOST}
    assert {v["metric"] for v in offline} == {"step", "data_wait"}
    # Acceptance: per-host goodput buckets sum to that host's
    # wall-clock within 5%.
    for h in summary["hosts"]:
        gp = summary["goodput_by_host"][str(h)]
        assert gp is not None
        assert sum(gp["buckets"].values()) == pytest.approx(
            gp["wall_s"], rel=0.05)
    # The slow host shows MORE step time, not more idle (it is slow,
    # not waiting).
    slow = summary["goodput_by_host"][str(SLOW_HOST)]
    fast = summary["goodput_by_host"]["0"]
    assert slow["buckets"]["step"] > 2 * fast["buckets"]["step"]
    # The coordinator's collectives audit surfaces in the merged view.
    assert summary["collectives"]["bytes_per_step"] == 4096
    assert summary["loss"]["last"] == 1.0


def test_render_multihost_names_the_straggler(tmp_path):
    summary = aggregate.aggregate_run(str(_pod_dir(tmp_path)))
    text = aggregate.render_multihost(summary)
    assert f"STRAGGLER (offline): host {SLOW_HOST}" in text
    assert "goodput by host:" in text
    assert "checkpoint barrier spread" in text
    assert "collectives: 0.00 MB/step" in text  # 4096 B rounds down


def test_summarizer_cli_autodetects_multihost(tmp_path, capsys):
    from distributed_training_tpu.telemetry.summarize import main
    run_dir = str(_pod_dir(tmp_path))
    assert main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "multi-host run:" in out and "STRAGGLER" in out
    assert main([run_dir, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["multihost"] and parsed["schema"] == 1
    merged_path = os.path.join(run_dir, "merged.jsonl")
    assert main([run_dir, "--write-merged", merged_path]) == 0
    capsys.readouterr()
    assert os.path.isfile(merged_path)


# -- the shared straggler rule --------------------------------------------


def test_flag_stragglers_threshold_and_floor():
    base = {0: {"step": 0.1, "data_wait": 0.01},
            1: {"step": 0.1, "data_wait": 0.01},
            2: {"step": 0.25, "data_wait": 0.01}}
    verdicts = flag_stragglers(base, threshold=1.5)
    assert [v["host"] for v in verdicts] == [2]
    assert verdicts[0]["metric"] == "step"
    assert verdicts[0]["ratio"] == pytest.approx(2.5)
    # Under threshold: nothing.
    assert not flag_stragglers(base, threshold=3.0)
    # Absolute floor: 3us vs 1us data_wait (prefetch keeping up
    # everywhere) is not a 3x straggler.
    tiny = {0: {"step": 0.1, "data_wait": 1e-6},
            1: {"step": 0.1, "data_wait": 1e-6},
            2: {"step": 0.1, "data_wait": 3e-6}}
    assert not flag_stragglers(tiny)
    # Fewer than 2 hosts with data: no verdicts, no crash.
    assert not flag_stragglers({0: {"step": 0.1, "data_wait": None}})


class _RT:
    def __init__(self, process_index=0, process_count=4):
        self.process_index = process_index
        self.process_count = process_count


def _table(slow_ratio, n=10.0):
    """Gathered (hosts, [step_sum, wait_sum, n]) table: host 3 slow."""
    rows = [[1.0, 0.1, n]] * 3 + [[slow_ratio, 0.1 * slow_ratio, n]]
    return np.asarray(rows, dtype=np.float32)


def test_straggler_detector_disabled_paths(tmp_path):
    assert not StragglerDetector(_RT(process_count=1), every=10).enabled
    assert not StragglerDetector(_RT(), every=0).enabled
    det = StragglerDetector(_RT(process_count=1), every=10,
                            gather=lambda p: (_ for _ in ()).throw(
                                AssertionError("gather must not run")))
    det.record_step(0.1, 0.01)
    assert det.maybe_exchange(10) is None


def test_straggler_detector_persist_gates_verdict(tmp_path):
    tel = telemetry.Telemetry(
        events_jsonl=str(tmp_path / "e.jsonl"))
    tables = iter([_table(2.5), _table(2.5)])
    det = StragglerDetector(_RT(), telemetry=tel, every=10, persist=2,
                            gather=lambda p: next(tables))
    for s in range(1, 21):
        det.record_step(0.1, 0.01)
        out = det.maybe_exchange(s)
        if s == 10:
            # First flagged window: a verdict candidate, not yet
            # persistent (one slow window is noise).
            assert out["verdicts"] and not out["persistent"]
            assert det.watchdog_info() == {}
        elif s == 20:
            assert out["persistent"]
            assert f"host {SLOW_HOST} is 2.5x median" in \
                out["persistent"][0]
            assert "straggler" in det.watchdog_info()
        else:
            assert out is None  # off cadence: no gather, no event
    rows = [r for r in _read_jsonl(str(tmp_path / "e.jsonl"))
            if r["kind"] == "straggler"]
    assert len(rows) == 2 and rows[-1]["persistent"]


def test_straggler_detector_streak_resets_on_clean_window(tmp_path):
    tel = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"))
    tables = iter([_table(2.5), _table(1.0), _table(2.5)])
    det = StragglerDetector(_RT(), telemetry=tel, every=1, persist=2,
                            gather=lambda p: next(tables))
    for s in (1, 2, 3):
        det.record_step(0.1, 0.01)
        out = det.maybe_exchange(s)
        # The clean window at s=2 broke the streak: never persistent.
        assert not out["persistent"]


def test_straggler_detector_disables_on_gather_failure(tmp_path):
    """Observability must not take down the loop it observes: a
    backend without cross-process gathers (multi-process CPU) fails
    symmetrically on every host, so the detector disarms for the rest
    of the run instead of raising into the training loop."""
    tel = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"))

    def broken_gather(payload):
        raise RuntimeError("Multiprocess computations aren't "
                           "implemented on the CPU backend.")

    det = StragglerDetector(_RT(), telemetry=tel, every=1,
                            gather=broken_gather)
    det.record_step(0.1, 0.01)
    assert det.maybe_exchange(1) is None
    assert not det.enabled
    det.record_step(0.1, 0.01)  # further calls are cheap no-ops
    assert det.maybe_exchange(2) is None
    rows = _read_jsonl(str(tmp_path / "e.jsonl"))
    assert [r["kind"] for r in rows if r["kind"].startswith(
        "straggler")] == ["straggler_disabled"]


def test_straggler_detector_payload_is_window_mean(tmp_path):
    """The exchange ships window SUMS + count; per-host means must
    come out right and the window must reset after each exchange."""
    seen = []

    def gather(payload):
        seen.append(payload.copy())
        return np.tile(payload, (4, 1))

    det = StragglerDetector(_RT(), telemetry=telemetry.current(),
                            every=2, gather=gather)
    for s in range(1, 5):
        det.record_step(0.2, 0.05)
        det.maybe_exchange(s)
    assert len(seen) == 2
    for p in seen:  # 2 steps/window x (0.2 step, 0.05 wait), n=2
        assert p == pytest.approx([0.4, 0.1, 2.0], abs=1e-6)


# -- collective-traffic accounting ----------------------------------------


def test_collectives_nonzero_for_sharded_zero_for_single():
    """Acceptance: a jitted step over a sharded mesh reports nonzero
    collective bytes, attributed to the right mesh axes; a
    single-device program reports zero."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "fsdp"))
    x = jax.device_put(
        jnp.ones((8, 16), jnp.float32),
        NamedSharding(mesh, PartitionSpec("dp", "fsdp")))
    text = jax.jit(lambda v: (v * 2).sum()).lower(x).compile().as_text()
    rep = audit_hlo_text(text, mesh=mesh)
    assert rep["schema"] == 1
    assert rep["total_collectives"] > 0
    assert rep["bytes_per_step"] > 0
    # The full reduction communicates over both axes; every byte is
    # attributed to a known axis (nothing lands in "unknown").
    assert set(rep["by_axis"]) <= {"dp", "fsdp", "dp+fsdp"}
    assert sum(v["bytes"] for v in rep["by_axis"].values()) == \
        rep["bytes_per_step"]

    single = jax.jit(lambda v: v * 2).lower(
        jnp.ones((8,), jnp.float32)).compile().as_text()
    rep1 = audit_hlo_text(single)
    assert rep1["total_collectives"] == 0
    assert rep1["bytes_per_step"] == 0


def test_parse_replica_groups_both_forms():
    explicit = "replica_groups={{0,1},{2,3}}"
    assert parse_replica_groups(explicit) == [(0, 1), (2, 3)]
    # Iota form: 2 groups of 2 over a [2,2] iota transposed — groups
    # are the COLUMNS of the untransposed arrangement.
    iota = "replica_groups=[2,2]<=[2,2]T(1,0)"
    assert parse_replica_groups(iota) == [(0, 2), (1, 3)]
    assert parse_replica_groups("no groups here") is None


def test_trainer_emits_collectives_event(cpu8, tmp_path):
    """The trainer's one-shot audit after the first (compile) step:
    a `collectives` event with nonzero dp-axis bytes on the 8-device
    DDP mesh, consumed by the single-run summarizer."""
    from distributed_training_tpu.checkpoint import Checkpointer
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (
        ShardedDataLoader, SyntheticRegressionDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.train.trainer import Trainer

    cfg = Config()
    cfg.train.batch_size = 8
    cfg.train.total_epochs = 1
    cfg.train.save_every = 0
    cfg.train.log_every = 0
    cfg.train.dataset_size = 16
    cfg.train.metrics_jsonl = str(tmp_path / "run" / "metrics.jsonl")
    cfg.train.events_jsonl = str(tmp_path / "run" / "events.jsonl")
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    model = build_model("mlp", input_size=20, output_size=1,
                        loss="mse")
    ds = SyntheticRegressionDataset(size=16, in_dim=20, out_dim=1,
                                    seed=0)
    loader = ShardedDataLoader(ds, cpu8, batch_size=8)
    trainer = Trainer(cfg, cpu8, model, loader,
                      Checkpointer(str(tmp_path / "run" / "ckpt")))
    trainer.train()
    events = _read_jsonl(cfg.train.events_jsonl)
    colls = [e for e in events if e["kind"] == "collectives"]
    assert len(colls) == 1, "one-shot audit must emit exactly once"
    rep = colls[0]
    # DDP grad sync across dp=8: all-reduce traffic on the dp axis.
    assert rep["bytes_per_step"] > 0
    assert rep["by_kind"]["all-reduce"]["count"] >= 1
    assert rep["mesh"] == {"dp": 8}
    assert set(rep.get("by_axis", {})) == {"dp"}
    from distributed_training_tpu.telemetry.summarize import (
        render, summarize_run)
    summary = summarize_run(str(tmp_path / "run"))
    assert summary["collectives"]["bytes_per_step"] == \
        rep["bytes_per_step"]
    assert "collectives:" in render(summary)


def test_trainer_audit_failure_does_not_kill_training(
        cpu8, tmp_path, monkeypatch):
    """Observability must not take down the loop it observes: a
    crashing audit logs and training completes, with no collectives
    event."""
    from distributed_training_tpu.checkpoint import Checkpointer
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (
        ShardedDataLoader, SyntheticRegressionDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.train.trainer import Trainer

    cfg = Config()
    cfg.train.batch_size = 8
    cfg.train.total_epochs = 1
    cfg.train.save_every = 0
    cfg.train.log_every = 0
    cfg.train.dataset_size = 16
    cfg.train.metrics_jsonl = str(tmp_path / "run" / "metrics.jsonl")
    cfg.train.events_jsonl = str(tmp_path / "run" / "events.jsonl")
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    model = build_model("mlp", input_size=20, output_size=1,
                        loss="mse")
    ds = SyntheticRegressionDataset(size=16, in_dim=20, out_dim=1,
                                    seed=0)
    loader = ShardedDataLoader(ds, cpu8, batch_size=8)
    trainer = Trainer(cfg, cpu8, model, loader,
                      Checkpointer(str(tmp_path / "run" / "ckpt")))
    monkeypatch.setattr(
        Trainer, "collectives_report",
        lambda self, batch: (_ for _ in ()).throw(
            RuntimeError("audit boom")))
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])
    events = _read_jsonl(cfg.train.events_jsonl)
    assert not [e for e in events if e["kind"] == "collectives"]


# -- 2-process CPU end-to-end (the real per-host layout) ------------------


@pytest.mark.slow
def test_two_process_run_produces_mergeable_streams(tmp_path, capsys):
    """launch/local.py drives the real CLI as a simulated 2-host pod:
    each host writes host_<i>/events.jsonl (host-tagged, with a
    clock_sync record), the coordinator emits the collectives audit,
    the straggler exchange runs, and the multi-host summarizer
    renders one merged report without error."""
    from distributed_training_tpu.launch import local as launch_local

    out_dir = str(tmp_path / "out")
    run_dir = os.path.join(out_dir, "default")
    procs = launch_local.launch_local(
        [
            "-m", "distributed_training_tpu.train",
            f"run.output_dir={out_dir}",
            f"train.snapshot_path={tmp_path / 'ckpt'}",
            "train.total_epochs=2",
            "train.dataset_size=64",
            "train.batch_size=8",
            "train.log_every=0",
            "train.save_every=0",
            "train.straggler_every=1",
        ],
        num_processes=2,
        devices_per_process=2,
        log_dir=str(tmp_path / "logs"),
        env={"JAX_PLATFORMS": "cpu"},
    )
    code = launch_local.wait(procs, timeout=420)
    logs = "\n".join(
        open(p.log_path).read() for p in procs if p.log_path)
    if code != 0 and ("Multiprocess computations aren't implemented"
                      in logs):
        # Pre-existing container limitation (the seed's 2-process
        # training test fails on it too, inside orbax's directory
        # sync): this jax build's CPU backend cannot run ANY
        # cross-process computation, so no multi-process training path
        # can execute here. The test stays live for capable backends.
        pytest.skip("jax CPU backend lacks multiprocess computations "
                    "in this environment")
    assert code == 0, f"multi-process run failed:\n{logs[-4000:]}"

    # Per-host layout, every record host-tagged, clock sync present.
    streams = aggregate.load_host_streams(run_dir)
    assert sorted(streams) == [0, 1]
    for h, events in streams.items():
        assert all(e.get("host") == h for e in events)
        kinds = {e["kind"] for e in events}
        assert "clock_sync" in kinds and "span" in kinds
        # The exchange ran on BOTH hosts (every host computes the
        # same verdicts from the same gathered table).
        assert "straggler" in kinds
    # Coordinator-only one-shot collectives audit: 4-device DDP mesh
    # means nonzero all-reduce bytes.
    colls = [e for e in streams[0]
             if e["kind"] == "collectives"]
    assert len(colls) == 1 and colls[0]["bytes_per_step"] > 0
    assert not [e for e in streams[1] if e["kind"] == "collectives"]

    # The merged report renders from the real run dir.
    from distributed_training_tpu.telemetry.summarize import main
    assert main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "multi-host run:" in out and "goodput by host:" in out
    assert main([run_dir, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["hosts"] == [0, 1]
    for h in ("0", "1"):
        gp = summary["goodput_by_host"][h]
        assert sum(gp["buckets"].values()) == pytest.approx(
            gp["wall_s"], rel=0.05)
    assert summary["collectives"]["bytes_per_step"] > 0
