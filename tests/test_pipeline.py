"""Pipeline parallelism tests: GPipe schedule correctness vs the plain
layer scan, end-to-end training equivalence, and composition with data
parallel axes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticLMDataset)
from distributed_training_tpu.models.transformer import (
    Transformer, TransformerConfig)
from distributed_training_tpu.parallel.pipeline import pipeline_apply
from distributed_training_tpu.runtime import fake_cpu_runtime
from distributed_training_tpu.train.trainer import Trainer

# This container's pinned jax runs the Pallas kernels in interpret
# mode and the ring/pipeline numerics at minutes per test — far over
# the tier-1 wall-clock budget (the whole file was broken-at-import
# at seed, so the fast gate never paid for it). The fast gate still
# COMPILES these paths every run (the analysis SPMD audit target
# lowers ring attention under the full sharded train step; the
# test_benchmarks contract tests compile the strategy matrix); the
# kernel/numerics suites here run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def test_pipeline_apply_matches_sequential():
    """The wavefront schedule must equal running all layers in order."""
    rt = fake_cpu_runtime(8, pp=4)
    L, B, S, D = 8, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    w = jax.random.normal(ks[0], (L, D, D)) * 0.1
    b = jax.random.normal(ks[1], (L, D)) * 0.1
    x = jax.random.normal(ks[2], (B, S, D))

    def stage_body(stage_params, layer_ids, xb, mb_idx):
        def body(carry, inp):
            layer, _lid = inp
            x, aux = carry
            x = jnp.tanh(x @ layer["w"] + layer["b"])
            return (x, aux + jnp.sum(x ** 2)), None
        (xb, aux), _ = jax.lax.scan(
            body, (xb, jnp.zeros((), jnp.float32)),
            (stage_params, layer_ids))
        return xb, aux

    out, aux = pipeline_apply(stage_body, {"w": w, "b": b}, x, rt.mesh,
                              num_microbatches=4)

    ref = x
    ref_aux = 0.0
    for i in range(L):
        ref = jnp.tanh(ref @ w[i] + b[i])
        ref_aux += jnp.sum(ref ** 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)


def test_pipeline_gradients_match_sequential():
    rt = fake_cpu_runtime(8, pp=4)
    L, B, S, D = 4, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    w = jax.random.normal(ks[0], (L, D, D)) * 0.2
    x = jax.random.normal(ks[1], (B, S, D))

    def stage_body(stage_params, layer_ids, xb, mb_idx):
        def body(carry, inp):
            layer, _lid = inp
            h, aux = carry
            return (jnp.tanh(h @ layer), aux), None
        (xb, aux), _ = jax.lax.scan(
            body, (xb, jnp.zeros((), jnp.float32)),
            (stage_params, layer_ids))
        return xb, aux

    def loss_pp(w):
        out, _ = pipeline_apply(stage_body, w, x, rt.mesh,
                                num_microbatches=2)
        return jnp.sum(out ** 2)

    def loss_seq(w):
        h = x
        for i in range(L):
            h = jnp.tanh(h @ w[i])
        return jnp.sum(h ** 2)

    gp = jax.jit(jax.grad(loss_pp))(w)
    gs = jax.jit(jax.grad(loss_seq))(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                               rtol=1e-4, atol=1e-5)


def test_pp_transformer_training_matches_dp():
    """Full train steps: transformer on (dp=2, pp=4) == plain dp=2."""
    losses = {}
    for tag, ndev, axes in (("dp", 2, {}), ("pp", 8, {"pp": 4})):
        rt = fake_cpu_runtime(ndev, **axes)
        assert rt.data_shard_count == 2
        cfg = Config()
        cfg.train.batch_size = 4
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            pp_microbatches=4))
        ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=4, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["pp"],
                               rtol=1e-5, atol=1e-6)


def test_pipeline_validation():
    rt = fake_cpu_runtime(8, pp=4)
    w = jnp.zeros((6, 4, 4))  # 6 layers not divisible by 4 stages
    x = jnp.zeros((4, 2, 4))

    def stage_body(p, lids, xb, mb_idx):
        return xb, jnp.zeros((), jnp.float32)

    with pytest.raises(ValueError, match="layers"):
        pipeline_apply(stage_body, w, x, rt.mesh, num_microbatches=2)
    w2 = jnp.zeros((4, 4, 4))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_body, w2, x, rt.mesh, num_microbatches=3)


def test_pp_moe_aux_matches_dp():
    """Regression: the MoE load-balancing aux is a batch-mean statistic;
    under pp it was summed over microbatches (x M inflation)."""
    aux = {}
    for tag, ndev, axes in (("dp", 2, {}), ("pp", 8, {"pp": 4})):
        rt = fake_cpu_runtime(ndev, **axes)
        cfg = Config()
        cfg.train.batch_size = 4
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            pp_microbatches=4, moe_num_experts=4))
        ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=4, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        m = trainer.train_step(next(iter(loader.epoch(0))))
        aux[tag] = float(m["moe_aux"])
    # The aux is a product of batch-mean statistics, so the microbatch
    # mean differs from the full-batch value at second order (~0.2%
    # here) — inherent to microbatched MoE. The regression guarded
    # against is the factor-of-M inflation (400% at M=4).
    np.testing.assert_allclose(aux["dp"], aux["pp"], rtol=0.02)


def test_pp_microbatch_autodivisor():
    """B=6 with pp_microbatches=4 must pick M=3, not crash."""
    rt = fake_cpu_runtime(8, pp=4)
    model = Transformer(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=4,
        max_seq_len=16, dtype="float32", attention_impl="naive",
        pp_microbatches=4))
    model.bind_mesh(rt.mesh)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    tokens = jnp.zeros((6, 9), jnp.int32)
    loss, _ = jax.jit(lambda p, b: model.loss(p, b, jax.random.PRNGKey(0)))(
        params, {"tokens": tokens})
    assert np.isfinite(float(loss))


def test_pp_microbatch_autodivisor_respects_data_shards():
    """Regression: B=4 on a dp=2,pp=2 mesh with pp_microbatches=4 must
    pick M=2 (per-microbatch batch stays divisible by the dp shard
    count), not M=4 (which makes shard_map reject batch dim 1)."""
    rt = fake_cpu_runtime(4, pp=2, dp=2)
    model = Transformer(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=4,
        max_seq_len=16, dtype="float32", attention_impl="naive",
        pp_microbatches=4))
    model.bind_mesh(rt.mesh)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    tokens = jnp.zeros((4, 9), jnp.int32)
    loss, _ = jax.jit(lambda p, b: model.loss(p, b, jax.random.PRNGKey(0)))(
        params, {"tokens": tokens})
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("M", [2, 4, 6])
def test_interleaved_matches_sequential(M):
    """The interleaved virtual-stage schedule must equal the plain
    layer scan (true global layer order, despite the permuted device
    storage)."""
    rt = fake_cpu_runtime(8, pp=4)
    L, B, S, D = 8, 12, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    w = jax.random.normal(ks[0], (L, D, D)) * 0.1
    b = jax.random.normal(ks[1], (L, D)) * 0.1
    x = jax.random.normal(ks[2], (B, S, D))

    def stage_body(stage_params, layer_ids, xb, mb_idx):
        def body(carry, inp):
            layer, _lid = inp
            x, aux = carry
            x = jnp.tanh(x @ layer["w"] + layer["b"])
            return (x, aux + jnp.sum(x ** 2)), None
        (xb, aux), _ = jax.lax.scan(
            body, (xb, jnp.zeros((), jnp.float32)),
            (stage_params, layer_ids))
        return xb, aux

    out, aux = pipeline_apply(stage_body, {"w": w, "b": b}, x, rt.mesh,
                              num_microbatches=M,
                              schedule="interleaved", virtual_stages=2)
    ref = x
    ref_aux = 0.0
    for i in range(L):
        ref = jnp.tanh(ref @ w[i] + b[i])
        ref_aux += jnp.sum(ref ** 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)


def test_interleaved_gradients_match_sequential():
    rt = fake_cpu_runtime(8, pp=4)
    L, B, S, D = 8, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 2)
    w = jax.random.normal(ks[0], (L, D, D)) * 0.2
    x = jax.random.normal(ks[1], (B, S, D))

    def stage_body(stage_params, layer_ids, xb, mb_idx):
        def body(carry, inp):
            layer, _lid = inp
            h, aux = carry
            return (jnp.tanh(h @ layer), aux), None
        (xb, aux), _ = jax.lax.scan(
            body, (xb, jnp.zeros((), jnp.float32)),
            (stage_params, layer_ids))
        return xb, aux

    def loss_il(w):
        out, _ = pipeline_apply(stage_body, w, x, rt.mesh,
                                num_microbatches=2,
                                schedule="interleaved",
                                virtual_stages=2)
        return jnp.sum(out ** 2)

    def loss_seq(w):
        h = x
        for i in range(L):
            h = jnp.tanh(h @ w[i])
        return jnp.sum(h ** 2)

    gi = jax.jit(jax.grad(loss_il))(w)
    gs = jax.jit(jax.grad(loss_seq))(w)
    np.testing.assert_allclose(np.asarray(gi), np.asarray(gs),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_fewer_idle_ticks_than_gpipe():
    """VERDICT item 6 'Done' criterion: at M=pp the interleaved
    schedule idles v-fold fewer device-slots than GPipe (chunk-tick
    accounting; v=2 here)."""
    from distributed_training_tpu.parallel.pipeline import schedule_stats
    for pp in (2, 4, 8):
        g = schedule_stats(pp, pp, "gpipe", virtual_stages=2)
        i = schedule_stats(pp, pp, "interleaved", virtual_stages=2)
        assert i["idle"] < g["idle"], (pp, g, i)
        assert g["idle"] == 2 * i["idle"]  # v=2: exactly halved
        assert g["useful"] == i["useful"]


def test_pp_dropout_matches_pp1_at_single_microbatch():
    """Dropout masks derive from (global layer id, microbatch index,
    data-shard index), so pp=4 with M=1 and one data shard must
    reproduce the pp=1 plain-scan loss exactly (same shapes, same
    keys, same draws). With dp>1 the pipeline intentionally draws
    per-shard (decorrelated by the shard fold-in) and only statistical
    parity holds."""
    losses = {}
    for tag, ndev, axes in (("pp1", 1, {}), ("pp4", 4, {"pp": 4})):
        rt = fake_cpu_runtime(ndev, **axes)
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            dropout=0.3, pp_microbatches=1))
        model.bind_mesh(rt.mesh)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (4, 17)),
            jnp.int32)
        loss, _ = jax.jit(
            lambda p, b: model.loss(p, b, jax.random.PRNGKey(9),
                                    train=True))(
            params, {"tokens": tokens})
        losses[tag] = float(loss)
    assert losses["pp1"] == pytest.approx(losses["pp4"], rel=1e-6)


@pytest.mark.parametrize("schedule", ["gpipe", "interleaved"])
def test_pp_dropout_trains_with_microbatches(schedule):
    """Dropout + pp>1 + M>1: runs, finite, and actually drops (loss
    differs from the dropout-off model)."""
    rt = fake_cpu_runtime(8, pp=4)
    losses = {}
    for tag, rate in (("drop", 0.4), ("nodrop", 0.0)):
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=8, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            dropout=rate, pp_microbatches=2, pp_schedule=schedule))
        model.bind_mesh(rt.mesh)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (4, 17)),
            jnp.int32)
        loss, _ = jax.jit(
            lambda p, b: model.loss(p, b, jax.random.PRNGKey(5),
                                    train=True))(
            params, {"tokens": tokens})
        losses[tag] = float(loss)
        assert np.isfinite(losses[tag])
    assert losses["drop"] != pytest.approx(losses["nodrop"], rel=1e-9)


def test_interleaved_transformer_matches_gpipe():
    """Same model, same params: interleaved and GPipe schedules give
    the same loss (both equal the plain scan)."""
    rt = fake_cpu_runtime(8, pp=4)
    losses = {}
    for sched in ("gpipe", "interleaved"):
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=8, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            pp_microbatches=2, pp_schedule=sched))
        model.bind_mesh(rt.mesh)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, 64, (4, 17)),
            jnp.int32)
        loss, _ = jax.jit(
            lambda p, b: model.loss(p, b, jax.random.PRNGKey(0)))(
            params, {"tokens": tokens})
        losses[sched] = float(loss)
    assert losses["gpipe"] == pytest.approx(losses["interleaved"],
                                            rel=1e-6)


def test_pp_composes_with_grad_accum():
    """Two microbatching levels at once — the trainer's grad-accum scan
    over the pipeline's own pp-microbatch wavefront — must reproduce
    the plain-dp trajectory at the same global batch. Shapes chosen so
    the pp autodivisor really picks M=2 (per-accum-chunk B=4 over
    dp=2 shards): a dp=4 variant would silently degrade to M=1 and
    test nothing."""
    def run(ndev, axes, accum, bs):
        rt = fake_cpu_runtime(ndev, **axes)
        cfg = Config()
        cfg.train.batch_size = bs
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.optimizer = "adamw"
        cfg.train.learning_rate = 0.01
        cfg.train.grad_accum_steps = accum
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive",
            pp_microbatches=2))
        ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=bs,
                                   shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        return [float(trainer.train_step(b)["loss"])
                for b in loader.epoch(0)]

    base = run(2, {}, 1, 4)                       # global batch 8
    pp_accum = run(4, {"pp": 2, "dp": 2}, 2, 4)   # global batch 8
    np.testing.assert_allclose(base, pp_accum, rtol=1e-5, atol=1e-6)
