"""Checkpoint round-trip and resume tests (SURVEY.md §4.4: formalizing
the reference's resume-by-construction into save→kill→resume tests)."""

import jax
import numpy as np

from distributed_training_tpu.checkpoint import Checkpointer
from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models.mlp import MLP
from distributed_training_tpu.runtime import fake_cpu_runtime
from distributed_training_tpu.train.trainer import Trainer


def build(rt, tmp_path, epochs=4, save_every=1):
    cfg = Config()
    cfg.train.total_epochs = epochs
    cfg.train.save_every = save_every
    cfg.train.batch_size = 4
    cfg.train.dataset_size = 64
    cfg.train.learning_rate = 0.05
    cfg.train.log_every = 0
    cfg.train.snapshot_path = str(tmp_path / "ckpt")
    ds = SyntheticRegressionDataset(size=64, seed=0, kind="linear")
    loader = ShardedDataLoader(ds, rt, batch_size=4, seed=cfg.train.seed)
    model = MLP(input_size=20, output_size=1)
    ckpt = Checkpointer(cfg.train.snapshot_path, async_save=False)
    return Trainer(cfg, rt, model, loader, ckpt), ckpt


def test_roundtrip_save_restore(cpu8, tmp_path):
    trainer, ckpt = build(cpu8, tmp_path, epochs=2)
    trainer.train()
    assert ckpt.latest_step() is not None
    params_after = jax.tree.map(np.asarray, trainer.state["params"])
    ckpt.close()

    # Fresh trainer with same config restores params + step + epoch.
    trainer2, ckpt2 = build(cpu8, tmp_path, epochs=2)
    assert trainer2.epochs_run == 2  # saved at epoch 1, resume at 2
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        trainer2.state["params"], params_after)
    ckpt2.close()


def test_resume_continues_not_restarts(cpu8, tmp_path):
    """Kill after 2 epochs, resume, finish 4 — total steps must equal an
    uninterrupted 4-epoch run (parity: epochs_run resume semantics,
    src/distributed_trainer.py:186)."""
    trainer, ckpt = build(cpu8, tmp_path, epochs=2)
    trainer.train()  # epochs 0,1
    steps_after_2 = int(trainer.state["step"])
    ckpt.close()

    trainer2, ckpt2 = build(cpu8, tmp_path, epochs=4)
    assert trainer2.epochs_run == 2
    trainer2.train()  # epochs 2,3
    assert int(trainer2.state["step"]) == steps_after_2 * 2
    ckpt2.close()


def test_restore_across_topology_change(tmp_path):
    """Save under dp=8, restore under fsdp=8 — the FULL_STATE_DICT
    'gather then reload anywhere' capability, without the gather."""
    rt_dp = fake_cpu_runtime(8)
    trainer, ckpt = build(rt_dp, tmp_path, epochs=1)
    trainer.train()
    params_saved = jax.tree.map(np.asarray, trainer.state["params"])
    ckpt.close()

    rt_fsdp = fake_cpu_runtime(8, fsdp=8)
    trainer2, ckpt2 = build(rt_fsdp, tmp_path, epochs=1)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        trainer2.state["params"], params_saved)
    ckpt2.close()


def test_optimizer_state_restored(cpu8, tmp_path):
    """The reference dropped optimizer state on resume (SURVEY.md §5.4);
    we assert it round-trips."""
    cfg_over = dict(optimizer="adamw", learning_rate=0.01)
    cfg = Config()
    for k, v in cfg_over.items():
        setattr(cfg.train, k, v)
    cfg.train.total_epochs = 1
    cfg.train.save_every = 1
    cfg.train.batch_size = 4
    cfg.train.dataset_size = 64
    cfg.train.log_every = 0
    cfg.train.snapshot_path = str(tmp_path / "ckpt")
    ds = SyntheticRegressionDataset(size=64, seed=0, kind="linear")
    loader = ShardedDataLoader(ds, cpu8, batch_size=4)
    model = MLP(input_size=20, output_size=1)
    ckpt = Checkpointer(cfg.train.snapshot_path, async_save=False)
    t1 = Trainer(cfg, cpu8, model, loader, ckpt)
    t1.train()
    opt_after = jax.tree.map(np.asarray, t1.state["opt_state"])
    ckpt.close()

    ckpt2 = Checkpointer(cfg.train.snapshot_path, async_save=False)
    t2 = Trainer(cfg, cpu8, model, loader, ckpt2)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        t2.state["opt_state"], opt_after)
    ckpt2.close()


def test_fresh_start_when_no_checkpoint(cpu8, tmp_path):
    trainer, ckpt = build(cpu8, tmp_path)
    assert trainer.epochs_run == 0
    ckpt.close()


def test_consolidated_export_roundtrip(cpu8, tmp_path):
    """gather_on_save: FSDP-sharded state exports ONE portable file
    whose contents equal the live (sharded) state — the reference's
    FULL_STATE_DICT gather, minus its deadlock (SURVEY.md §8 B6)."""
    from distributed_training_tpu.checkpoint import load_consolidated

    cfg = Config()
    cfg.train.total_epochs = 1
    cfg.train.save_every = 1
    cfg.train.batch_size = 4
    cfg.train.dataset_size = 32
    cfg.train.log_every = 0
    cfg.train.parallel_strategy = "fsdp"
    cfg.train.min_shard_elems = 1
    cfg.train.gather_on_save = True
    cfg.train.snapshot_path = str(tmp_path / "ckpt")
    ds = SyntheticRegressionDataset(size=32, seed=0, kind="linear")
    loader = ShardedDataLoader(ds, cpu8, batch_size=4,
                               seed=cfg.train.seed)
    model = MLP(input_size=20, output_size=1, hidden_sizes=(64,))
    ckpt = Checkpointer(cfg.train.snapshot_path, async_save=False)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt)
    assert trainer.strategy.gather_on_save
    trainer.train()
    ckpt.close()

    import glob
    files = glob.glob(str(tmp_path / "ckpt" / "consolidated_*.msgpack"))
    assert len(files) == 1, files
    state_dict, meta = load_consolidated(files[0])
    assert meta["step"] == trainer.global_step
    # Every param leaf matches the live state, fully gathered.
    live = jax.tree.map(np.asarray, trainer.state["params"])

    def walk(d, prefix=()):
        for k, v in d.items():
            if isinstance(v, dict):
                yield from walk(v, prefix + (k,))
            else:
                yield prefix + (k,), v

    live_flat = {k: v for k, v in walk(live)}
    saved_params = state_dict["params"]
    saved_flat = {k: v for k, v in walk(saved_params)}
    assert set(live_flat) == set(saved_flat)
    for key, v in live_flat.items():
        np.testing.assert_array_equal(v, saved_flat[key])
    # And the artifact is loadable with no mesh/jax state at all:
    # restore onto a DIFFERENT layout (ddp, replicated).
    rt2 = fake_cpu_runtime(8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    replicated = NamedSharding(rt2.mesh, P())
    restored = jax.tree.map(
        lambda x: jax.device_put(x, replicated), saved_params)
    for key, v in walk(jax.tree.map(np.asarray, restored)):
        np.testing.assert_array_equal(v, live_flat[key])


def test_offline_export_cli(cpu8, tmp_path):
    """checkpoint/export.py consolidates an existing Orbax dir into the
    same portable format as gather_on_save, without model/mesh."""
    import subprocess
    import sys

    from distributed_training_tpu.checkpoint import load_consolidated

    trainer, ckpt = build(cpu8, tmp_path, epochs=2)
    trainer.train()
    ckpt.close()
    live = jax.tree.map(np.asarray, trainer.state["params"])

    out = str(tmp_path / "exported.msgpack")
    # Strip the 8-device flag: the tool must consolidate a checkpoint
    # saved on a DIFFERENT topology (here: 8 devices -> 1).
    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_training_tpu.checkpoint.export",
         "--ckpt", str(tmp_path / "ckpt"), "--out", out],
        capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr[-500:]
    state_dict, meta = load_consolidated(out)
    assert meta["step"] == trainer.global_step

    def leaves(d, prefix=()):
        for k, v in d.items():
            if isinstance(v, dict):
                yield from leaves(v, prefix + (k,))
            else:
                yield prefix + (k,), v

    live_flat = dict(leaves(live))
    saved_flat = dict(leaves(state_dict["params"]))
    assert set(live_flat) == set(saved_flat)
    for key, val in live_flat.items():
        np.testing.assert_array_equal(val, saved_flat[key])
