"""Model zoo tests: shapes, gradients, convergence smoke, attention
numerics, registry integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.models import build_model
from distributed_training_tpu.models.base import count_params
from distributed_training_tpu.models.transformer import (
    Transformer, TransformerConfig, build_transformer,
)
from distributed_training_tpu.ops.attention import (_naive_attention,
                                                    dot_product_attention)


def tiny_cfg(**kw):
    base = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=4,
                max_seq_len=16, dtype="float32")
    base.update(kw)
    return TransformerConfig(**base)


def test_transformer_shapes_and_loss():
    model = Transformer(tiny_cfg())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 128)
    loss, metrics = model.loss(params, {"tokens": tokens},
                               jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    # random init ≈ uniform over vocab
    assert float(loss) == pytest.approx(np.log(128), rel=0.2)
    assert "perplexity" in metrics


def test_transformer_learns():
    model = Transformer(tiny_cfg())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 128)
    batch = {"tokens": tokens}

    import optax
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        (loss, _), g = jax.value_and_grad(
            lambda p: model.loss(p, batch, jax.random.PRNGKey(0)),
            has_aux=True)(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5  # memorizes a fixed batch


def test_transformer_rope_and_gqa():
    model = Transformer(tiny_cfg(pos_encoding="rope", n_kv_heads=2,
                                 tie_embeddings=False))
    params = model.init(jax.random.PRNGKey(0))
    assert params["attn"]["wk"].shape == (2, 32, 2, 8)
    tokens = jnp.zeros((1, 8), jnp.int32)
    loss, _ = model.loss(params, {"tokens": tokens}, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_transformer_remat_same_loss():
    a = Transformer(tiny_cfg(remat=False))
    b = Transformer(tiny_cfg(remat=True))
    params = a.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 128)
    la, _ = a.loss(params, {"tokens": tokens}, jax.random.PRNGKey(0))
    lb, _ = b.loss(params, {"tokens": tokens}, jax.random.PRNGKey(0))
    assert float(la) == pytest.approx(float(lb), rel=1e-6)
    # gradients also agree
    ga = jax.grad(lambda p: a.loss(p, {"tokens": tokens},
                                   jax.random.PRNGKey(0))[0])(params)
    gb = jax.grad(lambda p: b.loss(p, {"tokens": tokens},
                                   jax.random.PRNGKey(0))[0])(params)
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6), ga, gb)


def test_moe_transformer():
    model = build_transformer("moe_transformer", d_model=32, n_layers=2,
                              n_heads=4, max_seq_len=16, vocab_size=64,
                              moe_num_experts=4, dtype="float32")
    params = model.init(jax.random.PRNGKey(0))
    assert params["mlp"]["wi"].shape == (2, 4, 32, 128)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 64)
    loss, metrics = model.loss(params, {"tokens": tokens},
                               jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    assert "moe_aux" in metrics
    # aux is near 1 for near-uniform routing
    assert 0.5 < float(metrics["moe_aux"]) < 4.0


def test_presets_and_registry():
    m = build_model("gpt2_125m", kwargs_unused := None or {})
    assert m.cfg.d_model == 768 and m.cfg.n_layers == 12
    # ~124M params (GPT-2 small, tied embeddings)
    assert m.num_params() == pytest.approx(124e6, rel=0.05)
    with pytest.raises(ValueError):
        build_model("not_a_model")


def test_gqa_attention_matches_mha_when_equal():
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (2, 8, 4, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 4, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 4, 16))
    out = _naive_attention(q, k, v, causal=True)
    # against a straightforward per-head loop
    ref = np.zeros_like(out)
    for h in range(4):
        logits = np.asarray(q[:, :, h] @ np.swapaxes(k[:, :, h], 1, 2))
        logits = logits / np.sqrt(16)
        mask = np.tril(np.ones((8, 8), bool))
        logits = np.where(mask, logits, -np.inf)
        p = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        ref[:, :, h] = np.asarray(p @ v[:, :, h])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_attention_causality():
    """Future tokens must not influence earlier outputs."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 8))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 2, 8))
    out1 = dot_product_attention(q, k, v, causal=True, impl="naive")
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out2 = dot_product_attention(q, k2, v2, causal=True, impl="naive")
    np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                               np.asarray(out2[:, :-1]), rtol=1e-5)


def test_resnet():
    model = build_model("resnet18")
    params = model.init(jax.random.PRNGKey(0))
    # standard ResNet-18 ~11M params
    assert count_params(params) == pytest.approx(11.2e6, rel=0.05)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits = model.apply(params, x)
    assert logits.shape == (2, 10)
    loss, metrics = model.loss(
        params, {"x": x, "y": jnp.array([1, 2])}, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_flops_accounting_positive():
    m = build_transformer("gpt2_125m")
    assert m.flops_per_token(1024) > 6 * 100e6
    r = build_model("resnet18")
    assert r.flops_per_sample() > 1e8


def test_remat_policies_do_not_recompute_flash_kernel():
    """remat_policy="mlp"/"selective" must not re-run the forward
    attention kernel in the backward: the flash custom-VJP names its
    residuals (flash_out/flash_lse) and both policy allow-lists carry
    those names. Regression pin for the measured r4 failure mode
    (31.8 ms/step of rematted pallas_call at batch 32): without the
    names, ``save_only_these_names`` drops the residuals and the remat
    region re-launches the kernel — the backward scan body held THREE
    pallas_calls instead of two (dq, dkv)."""
    import jax.extend.core as jex_core

    def pallas_paths(jaxpr, path=""):
        found = []
        for e in jaxpr.eqns:
            if e.primitive.name == "pallas_call":
                found.append(path)
            for v in e.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if isinstance(item, jex_core.ClosedJaxpr):
                        found += pallas_paths(
                            item.jaxpr, f"{path}/{e.primitive.name}")
                    elif isinstance(item, jex_core.Jaxpr):
                        found += pallas_paths(
                            item, f"{path}/{e.primitive.name}")
        return found

    for policy in ("mlp", "selective"):
        model = Transformer(tiny_cfg(
            max_seq_len=256, d_model=64, n_heads=2,
            attention_impl="flash", remat=True, remat_policy=policy))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 129), jnp.int32)
        jx = jax.make_jaxpr(jax.grad(
            lambda p: model.loss(p, {"tokens": tokens},
                                 jax.random.PRNGKey(1))[0]))(params)
        from collections import Counter
        counts = Counter(pallas_paths(jx.jaxpr))
        # forward layer scan: exactly the fwd kernel; backward remat
        # region: exactly the FUSED backward kernel (dq/dk/dv in one
        # pallas_call at this S), and crucially no fwd re-launch —
        # the broken state this test pins against was 3 here (fwd
        # recompute + the two split bwd kernels).
        assert counts["/scan"] == 1, (policy, counts)
        assert counts["/scan/remat2"] == 1, (policy, counts)


def test_mlp_pre_policy_skips_wi_matmul_recompute():
    """remat_policy="mlp_pre" saves the tagged pre-gelu tensor, so the
    backward remat region must hold exactly ONE fewer dot_general per
    scanned block than "mlp" (the wi-matmul recompute — 2*B*S*D*F
    FLOPs/layer, ~8% of the gpt2_125m step — replaced by an
    elementwise gelu recompute from the saved activation). Gradients
    must be identical: the policy changes what is stored, not what is
    computed."""
    import jax.extend.core as jex_core

    def remat_dots(jaxpr, inside_remat=False):
        n = 0
        for e in jaxpr.eqns:
            if inside_remat and e.primitive.name == "dot_general":
                n += 1
            inner = inside_remat or e.primitive.name == "remat2"
            for v in e.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if isinstance(item, jex_core.ClosedJaxpr):
                        n += remat_dots(item.jaxpr, inner)
                    elif isinstance(item, jex_core.Jaxpr):
                        n += remat_dots(item, inner)
        return n

    tokens = jnp.zeros((2, 9), jnp.int32)
    dots, grads = {}, {}
    for policy in ("mlp", "mlp_pre"):
        model = Transformer(tiny_cfg(remat=True, remat_policy=policy))
        params = model.init(jax.random.PRNGKey(0))
        grad_fn = jax.grad(
            lambda p: model.loss(p, {"tokens": tokens},
                                 jax.random.PRNGKey(1))[0])
        dots[policy] = remat_dots(jax.make_jaxpr(grad_fn)(params).jaxpr)
        grads[policy] = grad_fn(params)
    assert dots["mlp_pre"] == dots["mlp"] - 1, dots
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        grads["mlp"], grads["mlp_pre"])


def test_ring_remat_does_not_recompute_forward_ring():
    """Mirror of test_remat_policies_do_not_recompute_flash_kernel for
    attention_impl='ring' (ADVICE r4): the ring's custom VJP names its
    residuals (flash_out/flash_lse at the VJP boundary), so
    remat_policy='mlp' must not re-run the forward ring — including
    its ICI rotations — inside the backward remat region. Invariant
    pinned: the grad jaxpr's total ppermute count under remat='mlp'
    equals the no-remat count (fwd ring + reverse ring); a failure of
    checkpoint_name propagation through shard_map + the custom VJP
    would recompute the forward ring and inflate it."""
    import jax.extend.core as jex_core

    from distributed_training_tpu.runtime import fake_cpu_runtime

    def count_prim(jaxpr, prim):
        n = 0
        for e in jaxpr.eqns:
            if e.primitive.name == prim:
                n += 1
            for v in e.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if isinstance(item, jex_core.ClosedJaxpr):
                        n += count_prim(item.jaxpr, prim)
                    elif isinstance(item, jex_core.Jaxpr):
                        n += count_prim(item, prim)
        return n

    rt = fake_cpu_runtime(8, sp=2)
    counts = {}
    for label, extra in (("noremat", dict(remat=False)),
                         ("mlp", dict(remat=True,
                                      remat_policy="mlp"))):
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=32, dtype="float32", attention_impl="ring",
            **extra))
        model.bind_mesh(rt.mesh)
        tokens = jnp.zeros((4, 33), jnp.int32)
        jx = jax.make_jaxpr(jax.grad(
            lambda p: model.loss(p, {"tokens": tokens},
                                 jax.random.PRNGKey(1))[0]))(
            model.init(jax.random.PRNGKey(0)))
        counts[label] = count_prim(jx.jaxpr, "ppermute")
    assert counts["noremat"] > 0, counts
    assert counts["mlp"] == counts["noremat"], counts


def test_bhsd_fast_path_matches_naive():
    """attention_impl='flash' routes the block's attention natively in
    (B, H, S, D) — qkv einsums emit the kernel layout, rope follows,
    no wrapper transposes. Loss and gradients must match the
    BSHD/naive reference model on identical params, including rope,
    GQA, and a sliding window."""
    for extra in (dict(),
                  dict(pos_encoding="rope", n_kv_heads=2,
                       tie_embeddings=False),
                  dict(attention_window=96)):
        cfg = dict(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                   max_seq_len=256, dtype="float32", **extra)
        flash = Transformer(TransformerConfig(
            attention_impl="flash", **cfg))
        naive = Transformer(TransformerConfig(
            attention_impl="naive", **cfg))
        assert flash._bhsd_fast(256) and not naive._bhsd_fast(256)
        params = flash.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 129),
                                    0, 128)
        rng = jax.random.PRNGKey(2)
        lf, _ = flash.loss(params, {"tokens": tokens}, rng)
        ln, _ = naive.loss(params, {"tokens": tokens}, rng)
        assert float(lf) == pytest.approx(float(ln), rel=2e-5), extra
        gf = jax.grad(lambda p: flash.loss(
            p, {"tokens": tokens}, rng)[0])(params)
        gn = jax.grad(lambda p: naive.loss(
            p, {"tokens": tokens}, rng)[0])(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
            gf, gn)


def test_xent_chunk_rows_knob_is_loss_invariant():
    """cfg.xent_chunk_rows reaches ops/xent.py (the bench sweeps it on
    chip — chunk geometry trades live-buffer size for scan overhead)
    and must never change the loss."""
    kw = dict(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
              max_seq_len=64, dtype="float32")
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 33)), jnp.int32)
    losses = []
    for rows in (8, 2048):
        m = Transformer(TransformerConfig(xent_chunk_rows=rows, **kw))
        p = m.init(jax.random.PRNGKey(0))
        losses.append(float(m.loss(
            p, {"tokens": tokens}, jax.random.PRNGKey(1))[0]))
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)


def test_tp_indivisible_heads_demote_consistently():
    """When a bound mesh's tp does not divide the (kv) head counts,
    the flash kernel cannot take a head shard: dispatch demotes to
    naive AND _flash_active reports False, so the remat allow-lists
    save attn_out (which exists) rather than the flash residual names
    (which don't — saving the wrong set makes the backward silently
    recompute all attention, the r4 31.8 ms/step bug class)."""
    from distributed_training_tpu.runtime import fake_cpu_runtime

    rt = fake_cpu_runtime(8, tp=4, dp=2)
    model = Transformer(TransformerConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=256, dtype="float32", attention_impl="flash",
        remat=True, remat_policy="mlp"))
    model.bind_mesh(rt.mesh)
    # n_kv_heads=2 not divisible by tp=4 -> not shardable -> inactive.
    assert not model._tp_head_shardable()
    assert not model._flash_active(256)
    # The step still runs (naive path through the partitioner).
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((8, 33), jnp.int32)
    loss, _ = jax.jit(lambda p, t: model.loss(
        p, {"tokens": t}, jax.random.PRNGKey(1)))(params, tokens)
    assert np.isfinite(float(loss))
    # Divisible heads stay shardable/active (impl='flash' forces the
    # kernel; on this CPU host supported() would be False for 'auto').
    model2 = Transformer(TransformerConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        max_seq_len=256, dtype="float32", attention_impl="flash"))
    model2.bind_mesh(rt.mesh)
    assert model2._tp_head_shardable()
    assert model2._flash_active(256)


def test_sharded_flash_matches_naive_on_mesh():
    """Runtime parity for the shard_map flash path (the fix for
    'Mosaic kernels cannot be automatically partitioned'): with a
    bound dp2.fsdp2.tp2 mesh and attention_impl='flash' (forced, so
    the kernels run in interpret mode on this CPU mesh), loss and
    gradients match the unsharded naive reference — batch sharding,
    tp head sharding, GQA, and rope all through the shard_map
    wrapper."""
    from distributed_training_tpu.runtime import fake_cpu_runtime

    rt = fake_cpu_runtime(8, dp=2, fsdp=2, tp=2)
    kw = dict(vocab_size=64, d_model=64, n_layers=2, n_heads=4,
              n_kv_heads=2, max_seq_len=256, dtype="float32",
              pos_encoding="rope", tie_embeddings=False)
    flash = Transformer(TransformerConfig(attention_impl="flash",
                                          **kw))
    flash.bind_mesh(rt.mesh)
    naive = Transformer(TransformerConfig(attention_impl="naive",
                                          **kw))
    params = flash.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (4, 129)), jnp.int32)
    rng = jax.random.PRNGKey(1)
    lf, _ = jax.jit(lambda p, t: flash.loss(
        p, {"tokens": t}, rng))(params, tokens)
    ln, _ = naive.loss(params, {"tokens": tokens}, rng)
    np.testing.assert_allclose(float(lf), float(ln), rtol=2e-5)
    gf = jax.jit(jax.grad(lambda p: flash.loss(
        p, {"tokens": tokens}, rng)[0]))(params)
    gn = jax.grad(lambda p: naive.loss(
        p, {"tokens": tokens}, rng)[0])(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), gf, gn)
