"""Exactly-once streaming pipeline (data/stream.py): state roundtrip,
deterministic mixture/packing, resume-from-any-cut and world-resize
properties, source-level fault injection, and the trainer integration
that makes a mid-epoch preemption resume bit-identical."""

import json
import os

import numpy as np
import pytest

from distributed_training_tpu.data import (StreamSource,
                                           StreamingDataLoader)
from distributed_training_tpu.data.datasets import (SyntheticDocDataset,
                                                    SyntheticLMDataset)
from distributed_training_tpu.data.sampler import epoch_permutation
from distributed_training_tpu.data.stream import (StreamState,
                                                  StreamStateError,
                                                  pick_source)
from distributed_training_tpu.runtime import fake_cpu_runtime


def make_sources(vocab=50):
    return [
        StreamSource("lm", SyntheticLMDataset(
            size=64, seq_len=16, vocab_size=vocab, seed=1), weight=2.0),
        StreamSource("doc", SyntheticDocDataset(
            size=48, min_len=5, max_len=30, vocab_size=vocab, seed=2),
            weight=1.0),
    ]


def make_loader(rt, batch_size=2, pack_len=16, shuffle=True, seed=7,
                sources=None, **kw):
    return StreamingDataLoader(sources or make_sources(), rt,
                               batch_size=batch_size, pack_len=pack_len,
                               shuffle=shuffle, seed=seed, **kw)


def tokens_of(loader, epochs):
    """All batches of the given epochs as host arrays."""
    out = []
    for e in epochs:
        out.extend(np.asarray(b["tokens"]) for b in loader.epoch(e))
    return out


# --- state ------------------------------------------------------------------


def test_state_json_roundtrip():
    st = StreamState(7, ["a", "b"])
    st.step, st.samples, st.skipped = 3, 48, 1
    st.epochs, st.cursors = [1, 0], [4, 9]
    st.carry = {"source": 0, "epoch": 1, "pos": 3, "offset": 5}
    d = json.loads(json.dumps(st.to_dict()))
    back = StreamState.from_dict(d, 7, ["a", "b"])
    assert back.to_dict() == st.to_dict()


@pytest.mark.parametrize("mutate,err", [
    (lambda d: d.update(schema=99), "schema"),
    (lambda d: d.update(seed=8), "seed"),
    (lambda d: d["sources"].pop("b"), "sources"),
    # Order is stream identity: the source index keys the permutation
    # streams and breaks mixture ties — a reorder must be rejected,
    # not remapped (the positional carry would splice wrong docs).
    (lambda d: d.update(sources=dict(
        reversed(list(d["sources"].items())))), "order"),
])
def test_state_rejects_mismatches(mutate, err):
    st = StreamState(7, ["a", "b"])
    d = st.to_dict()
    mutate(d)
    with pytest.raises(StreamStateError):
        StreamState.from_dict(d, 7, ["a", "b"])


def test_epoch_permutation_pure_function():
    a = epoch_permutation(5, 3, 100, stream=1)
    b = epoch_permutation(5, 3, 100, stream=1)
    np.testing.assert_array_equal(a, b)
    assert sorted(a) == list(range(100))
    # distinct epochs / streams / seeds give distinct orders
    assert not np.array_equal(a, epoch_permutation(5, 4, 100, stream=1))
    assert not np.array_equal(a, epoch_permutation(5, 3, 100, stream=2))
    np.testing.assert_array_equal(
        epoch_permutation(5, 3, 10, shuffle=False), np.arange(10))


def test_pick_source_realizes_weights():
    weights = [3.0, 1.0]
    consumed = [0, 0]
    picks = []
    for _ in range(400):
        i = pick_source(weights, consumed)
        consumed[i] += 1
        picks.append(i)
    # Deficit round-robin realizes the target mixture to within 1 doc
    # at every prefix, not just in the limit.
    assert consumed[0] == 300 and consumed[1] == 100
    running = [0, 0]
    for n, i in enumerate(picks, 1):
        running[i] += 1
        assert abs(running[0] - 0.75 * n) <= 1


# --- packing ----------------------------------------------------------------


def test_packing_is_token_exact(cpu8):
    """Blocks are the doc stream re-chunked: no token lost, duplicated,
    or padded across any carry boundary."""
    dl = make_loader(cpu8, batch_size=1, pack_len=16)
    batches = tokens_of(dl, [0])
    packed = np.concatenate([b.reshape(-1) for b in batches])

    # Reference doc stream: replay the pure cursor functions.
    ref = make_loader(cpu8, batch_size=1, pack_len=16)
    st = ref.state
    toks = []
    while len(toks) < len(packed):
        _src, _row, t = ref._next_doc(st, 0)
        toks.extend(t.tolist())
    np.testing.assert_array_equal(packed, np.array(toks[:len(packed)]))


def test_unpacked_requires_uniform_rows(cpu8):
    with pytest.raises(ValueError, match="equal-length"):
        make_loader(cpu8, pack_len=0, sources=[
            StreamSource("a", SyntheticLMDataset(size=32, seq_len=8,
                                                 vocab_size=50, seed=1)),
            StreamSource("b", SyntheticLMDataset(size=32, seq_len=16,
                                                 vocab_size=50, seed=9)),
        ])
    # A ragged source (doc() protocol) is rejected at construction —
    # a doc-0 probe can't prove uniformity, and a mid-run mismatch
    # would be a deterministic crash loop.
    with pytest.raises(ValueError, match="ragged"):
        make_loader(cpu8, pack_len=0, sources=[
            StreamSource("d", SyntheticDocDataset(size=16, min_len=9,
                                                  max_len=9,
                                                  vocab_size=50)),
        ])
    dl = make_loader(cpu8, pack_len=0, sources=[
        StreamSource("a", SyntheticLMDataset(size=32, seq_len=8,
                                             vocab_size=50, seed=1)),
        StreamSource("b", SyntheticLMDataset(size=32, seq_len=8,
                                             vocab_size=50, seed=9)),
    ])
    b = next(iter(dl.epoch(0)))
    assert np.asarray(b["tokens"]).shape[1] == 9


# --- exactly-once properties ------------------------------------------------


@pytest.mark.parametrize("pack_len", [0, 16])
@pytest.mark.parametrize("shuffle", [True, False])
@pytest.mark.parametrize("cut", [1, 3, 6, 11])
def test_resume_from_any_cut_is_exactly_once(cpu8, cut, shuffle,
                                             pack_len):
    """save-state → restore → continue yields the identical stream an
    uninterrupted run produces, for arbitrary cut points across
    shuffle/packing configs (epoch boundaries included)."""
    sources = (make_sources() if pack_len else [
        StreamSource("a", SyntheticLMDataset(size=80, seq_len=8,
                                             vocab_size=50, seed=1), 2.0),
        StreamSource("b", SyntheticLMDataset(size=48, seq_len=8,
                                             vocab_size=50, seed=9), 1.0),
    ])
    kw = dict(batch_size=2, pack_len=pack_len, shuffle=shuffle,
              sources=sources)
    ref = make_loader(cpu8, **kw)
    want = tokens_of(ref, [0, 1])
    spe = ref.steps_per_epoch
    assert cut < 2 * spe

    a = make_loader(cpu8, **kw)
    got = []
    for e in range(2):
        if len(got) >= cut:
            break
        it = iter(a.epoch(e))
        for b in it:
            got.append(np.asarray(b["tokens"]))
            if len(got) >= cut:
                it.close()
                break
    state = json.loads(json.dumps(a.state_dict()))

    b_loader = make_loader(cpu8, **kw)
    b_loader.load_state_dict(state)
    for e in range(b_loader.resume_epoch, 2):
        got.extend(np.asarray(x["tokens"]) for x in b_loader.epoch(e))

    assert len(got) == len(want)
    for x, y in zip(got, want):
        np.testing.assert_array_equal(x, y)


def test_elastic_resize_mid_epoch_is_exactly_once(cpu8):
    """World N → N-1 mid-epoch: with a world-size-invariant global
    batch, the shrunken incarnation consumes exactly the remainder of
    the uninterrupted stream — the re-deal touches only rows not yet
    consumed. (cpu8 stands in for N=4 hosts x 2 rows; the shrunken
    world is 2 'hosts' x 4 rows.)"""
    rt4 = fake_cpu_runtime(4)
    rt2 = fake_cpu_runtime(2)
    ref = make_loader(cpu8, batch_size=2)        # global batch 16
    want = tokens_of(ref, [0])

    a = make_loader(rt4, batch_size=4)           # same global batch
    assert a.global_batch == ref.global_batch
    assert a.steps_per_epoch == ref.steps_per_epoch
    it = iter(a.epoch(0))
    got = [np.asarray(next(it)["tokens"]) for _ in range(3)]
    it.close()
    state = json.loads(json.dumps(a.state_dict()))

    b = make_loader(rt2, batch_size=8)           # N-1 analogue
    b.load_state_dict(state)
    got.extend(np.asarray(x["tokens"]) for x in b.epoch(0))

    assert len(got) == len(want)
    for x, y in zip(got, want):
        np.testing.assert_array_equal(x, y)


def test_seek_epoch_matches_consumed_stream(cpu8):
    dl = make_loader(cpu8, batch_size=2)
    tokens_of(dl, [0])
    fwd = make_loader(cpu8, batch_size=2)
    fwd.seek_epoch(1)
    assert fwd.state.to_dict() == dl.state.to_dict()
    with pytest.raises(StreamStateError, match="backwards"):
        fwd.seek_epoch(0)


def test_state_rejects_source_size_change(cpu8):
    """epoch_permutation(seed, e, n) depends on n: a corpus that grew
    or shrank across a restart is a different stream — cursors must
    not map into it."""
    a = make_loader(cpu8, batch_size=2)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    state = json.loads(json.dumps(a.state_dict()))

    grown = [
        StreamSource("lm", SyntheticLMDataset(
            size=96, seq_len=16, vocab_size=50, seed=1), weight=2.0),
        StreamSource("doc", SyntheticDocDataset(
            size=48, min_len=5, max_len=30, vocab_size=50, seed=2)),
    ]
    b = make_loader(cpu8, batch_size=2, sources=grown)
    with pytest.raises(StreamStateError, match="size"):
        b.load_state_dict(state)


def test_state_rejects_shuffle_change(cpu8):
    """shuffle toggles every permutation between shuffled and arange —
    same failure class as a seed change."""
    a = make_loader(cpu8, batch_size=2, shuffle=True)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    state = json.loads(json.dumps(a.state_dict()))
    b = make_loader(cpu8, batch_size=2, shuffle=False)
    with pytest.raises(StreamStateError, match="shuffle"):
        b.load_state_dict(state)


def test_source_faults_rejected_without_stream_loader():
    """A plan scheduling source-level kinds against a run with no
    train.data_sources is a drill that would silently never fire —
    the wiring check fails it loudly instead."""
    from distributed_training_tpu.resilience import faults
    plan = faults.parse_fault_plan("crash@4,data_corrupt@5:skip")
    faults.check_plan_hooks(plan, has_stream_sources=True)
    with pytest.raises(faults.FaultPlanError, match="source-level"):
        faults.check_plan_hooks(plan, has_stream_sources=False)
    faults.check_plan_hooks(
        faults.parse_fault_plan("crash@4,data_error@5"),
        has_stream_sources=False)


def test_state_rejects_global_batch_change(cpu8):
    """step/samples count in global-batch units: a legacy per-shard
    batch under an elastic resize changes the unit — reject so the
    trainer falls back honestly (global_batch_size keeps the unit
    invariant across world sizes)."""
    a = make_loader(cpu8, batch_size=2)        # global batch 16
    it = iter(a.epoch(0))
    next(it)
    it.close()
    state = json.loads(json.dumps(a.state_dict()))
    b = make_loader(cpu8, batch_size=3)        # global batch 24
    with pytest.raises(StreamStateError, match="global batch"):
        b.load_state_dict(state)


def test_pervasive_corruption_escalates_to_fatal(cpu8):
    from distributed_training_tpu.data.stream import (
        MAX_CONSECUTIVE_SKIPS, CorruptSampleError)

    class AllCorrupt:
        vocab_size = 50

        def __init__(self):
            self.calls = 0

        def __len__(self):
            return 8

        def batch(self, idx):
            self.calls += 1
            if self.calls == 1:  # the loader's row-length probe
                return {"tokens": np.zeros((len(idx), 17), np.int32)}
            raise CorruptSampleError("rotted shard", policy="skip")

    dl = make_loader(cpu8, batch_size=2, pack_len=16, prefetch_depth=0,
                     sources=[StreamSource("bad", AllCorrupt())])
    with pytest.raises(ValueError, match="consecutive corrupt"):
        next(iter(dl.epoch(0)))
    assert dl.state.step == 0  # nothing committed
    assert MAX_CONSECUTIVE_SKIPS >= 16


def test_epoch_must_contain_position(cpu8):
    dl = make_loader(cpu8, batch_size=2)
    with pytest.raises(ValueError, match="stream position"):
        list(dl.epoch(1))


def test_probe_dataset_surfaces_contract_checks(cpu8):
    """loader.dataset is the Trainer's contract-check surface: batch
    keys and the MAX source vocab (any source exceeding the model's
    embedding table must be caught) without touching the stream."""
    dl = make_loader(cpu8, batch_size=2)
    assert dl.dataset.vocab_size == 50
    assert dl.dataset.seq_len == dl.block_len - 1
    assert len(dl.dataset) == sum(len(s.dataset) for s in dl.sources)
    probe = dl.dataset.batch(np.array([0]))
    assert set(probe) == {"tokens"}
    assert probe["tokens"].shape == (1, dl.block_len)
    assert dl.state.step == 0  # probing consumed nothing


# --- source-level faults ----------------------------------------------------


def test_data_corrupt_skip_records_and_continues(cpu8, tmp_path):
    from distributed_training_tpu import telemetry
    from distributed_training_tpu.resilience import faults

    inj = faults.FaultInjector(
        "data_corrupt@2:source=lm:skip",
        ledger_path=str(tmp_path / "ledger.json"))
    events_path = str(tmp_path / "events.jsonl")
    telemetry.install(telemetry.Telemetry(events_jsonl=events_path))
    try:
        dl = make_loader(cpu8, batch_size=2, fault_injector=inj,
                         prefetch_depth=0)
        clean = make_loader(cpu8, batch_size=2)
        got = tokens_of(dl, [0])
        want = tokens_of(clean, [0])
    finally:
        telemetry.current().close()
        telemetry.uninstall()
    from distributed_training_tpu.telemetry.summarize import load_jsonl
    events = load_jsonl(events_path)
    skips = [e for e in events if e.get("kind") == "data_skip"]
    assert len(skips) == 1
    assert skips[0]["source"] == "lm"
    assert isinstance(skips[0]["sample_id"], int)
    assert dl.state.skipped == 1
    assert dl.state_dict()["skipped"] == 1
    # The skipped doc shifts the stream by one document: batches after
    # the skip differ from the clean run's, but the loader still
    # yields full epochs (the stream never stalls on a bad sample).
    assert len(got) == len(want)


def test_data_corrupt_fatal_kills_the_batch(cpu8, tmp_path):
    from distributed_training_tpu.resilience import faults

    inj = faults.FaultInjector(
        "data_corrupt@1:fatal",
        ledger_path=str(tmp_path / "ledger.json"))
    dl = make_loader(cpu8, batch_size=2, fault_injector=inj,
                     prefetch_depth=0)
    with pytest.raises(faults.InjectedCorruptData):
        next(iter(dl.epoch(0)))
    # One-shot: a restarted incarnation does not re-fire.
    inj2 = faults.FaultInjector(
        "data_corrupt@1:fatal",
        ledger_path=str(tmp_path / "ledger.json"))
    dl2 = make_loader(cpu8, batch_size=2, fault_injector=inj2,
                      prefetch_depth=0)
    next(iter(dl2.epoch(0)))


def test_real_corrupt_skip_survives_transient_retry(cpu8, tmp_path):
    """A deterministic CorruptSampleError(skip) followed by a
    transient OSError in the SAME batch: the rollback re-runs the
    batch (re-skipping the sample), but the data_skip event emits
    exactly once, after the batch commits — counter and event stream
    agree."""
    from distributed_training_tpu import telemetry
    from distributed_training_tpu.data.stream import CorruptSampleError

    class CorruptAndFlaky:
        """Row 2 is permanently corrupt (skip policy); the 10th
        single-row read raises a transient OSError, once."""

        def __init__(self, base):
            self.base = base
            self.reads = 0
            self.blipped = False
            self.vocab_size = base.vocab_size

        def __len__(self):
            return len(self.base)

        def batch(self, idx):
            self.reads += 1
            if 2 in np.asarray(idx):
                raise CorruptSampleError("checksum mismatch",
                                         policy="skip")
            if self.reads >= 10 and not self.blipped:
                self.blipped = True
                raise OSError("transient blip")
            return self.base.batch(idx)

    ds = CorruptAndFlaky(SyntheticLMDataset(size=40, seq_len=8,
                                            vocab_size=50, seed=1))
    events_path = str(tmp_path / "events.jsonl")
    telemetry.install(telemetry.Telemetry(events_jsonl=events_path))
    try:
        dl = make_loader(cpu8, batch_size=2, pack_len=0, shuffle=False,
                         prefetch_depth=0,
                         sources=[StreamSource("a", ds)])
        batch = np.asarray(next(iter(dl.epoch(0)))["tokens"])
    finally:
        telemetry.current().close()
        telemetry.uninstall()
    from distributed_training_tpu.telemetry.summarize import load_jsonl
    events = load_jsonl(events_path)
    assert ds.blipped
    assert len([e for e in events if e.get("kind") == "data_retry"]) == 1
    skips = [e for e in events if e.get("kind") == "data_skip"]
    assert len(skips) == 1 and skips[0]["sample_id"] == 2
    assert dl.state.skipped == 1
    # Row 2 never reaches the batch; the stream continues past it.
    np.testing.assert_array_equal(
        batch, ds.base.batch(np.array(
            [r for r in range(dl.global_batch + 1) if r != 2]))["tokens"])


def test_source_stall_grammar_and_fires(tmp_path):
    from distributed_training_tpu.resilience import faults

    plan = faults.parse_fault_plan(
        "source_stall@3:20ms:source=wiki,data_corrupt@5:skip")
    by_kind = {f.kind: f for f in plan}
    assert by_kind["source_stall"].source == "wiki"
    assert by_kind["source_stall"].stall_s == pytest.approx(0.02)
    assert by_kind["data_corrupt"].policy == "skip"
    assert by_kind["data_corrupt"].source is None
    assert by_kind["source_stall"].key == "source_stall@3:source=wiki"

    inj = faults.FaultInjector(plan,
                               ledger_path=str(tmp_path / "l.json"))
    inj.on_source(2, "wiki")    # before the scheduled step: no fire
    inj.on_source(3, "other")   # wrong source: no fire (stall)
    assert "source_stall@3:source=wiki" not in inj.fired
    inj.on_source(4, "wiki")    # at-or-after: first matching read
    assert "source_stall@3:source=wiki" in inj.fired


@pytest.mark.parametrize("bad", [
    "source_stall@3:source=wiki",      # missing duration
    "data_corrupt@3:500ms",            # duration on a corrupt fault
    "crash@3:source=wiki",             # source= on a non-source kind
    "data_stall@3:500ms:skip",         # policy on a non-corrupt kind
])
def test_fault_plan_rejects_bad_source_entries(bad):
    from distributed_training_tpu.resilience import faults
    with pytest.raises(faults.FaultPlanError):
        faults.parse_fault_plan(bad)


# --- trainer integration ----------------------------------------------------


def _stream_trainer(rt, tmp_path, epochs, guard=None, sources=None):
    from distributed_training_tpu.checkpoint import Checkpointer
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.train.trainer import Trainer

    cfg = Config()
    cfg.train.total_epochs = epochs
    cfg.train.batch_size = 2
    cfg.train.log_every = 0
    cfg.train.save_every = 100   # only forced (preemption) saves
    cfg.train.collectives_audit = False
    loader = StreamingDataLoader(
        sources or make_sources(vocab=32), rt, batch_size=2, pack_len=8,
        seed=cfg.train.seed, steps_per_epoch=4)
    model = build_model("transformer", vocab_size=32, d_model=16,
                        n_layers=1, n_heads=2, max_seq_len=16,
                        dtype="float32")
    ckpt = Checkpointer(os.path.join(str(tmp_path), "ckpt"))
    return Trainer(cfg, rt, model, loader, ckpt,
                   preemption_guard=guard), ckpt


def test_trainer_mid_epoch_preempt_resume_bit_identical(cpu8, tmp_path):
    """The acceptance property in-process: preempt mid-epoch, resume
    from the restored StreamState, finish — final params are
    bit-identical to an uninterrupted run's (no sample replayed or
    skipped, by construction of the identical stream)."""
    import jax

    from distributed_training_tpu.utils.preemption import PreemptionGuard

    ref, c_ref = _stream_trainer(cpu8, tmp_path / "ref", epochs=2)
    ref.train()
    c_ref.wait()
    c_ref.close()

    guard = PreemptionGuard()
    guard.trigger("test")        # stops after the FIRST step, mid-epoch
    t1, c1 = _stream_trainer(cpu8, tmp_path / "el", epochs=2,
                             guard=guard)
    t1.train()
    c1.wait()
    c1.close()
    assert t1.global_step == 1
    assert t1.loader.state.step == 1

    t2, c2 = _stream_trainer(cpu8, tmp_path / "el", epochs=2)
    assert int(t2.state["step"]) == 1
    assert t2.epochs_run == 0            # resumes INTO epoch 0, step 1
    assert t2.loader.state.step == 1     # restored cursor, not a replay
    t2.train()
    c2.wait()
    c2.close()

    assert t2.global_step == ref.global_step
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        t2.state["params"], ref.state["params"])


def test_trainer_fallback_replays_interrupted_epoch(cpu8, tmp_path):
    """A mid-epoch checkpoint whose stream state is unusable (here:
    the source set changed across the restart) must REPLAY the
    interrupted epoch from its start — skipping the remainder would
    silently drop data; the replay shows up honestly in the recovery
    accounting."""
    from distributed_training_tpu.utils.preemption import PreemptionGuard

    guard = PreemptionGuard()
    guard.trigger("test")
    t1, c1 = _stream_trainer(cpu8, tmp_path, epochs=2, guard=guard)
    t1.train()                 # stops after step 1, mid-epoch-0 save
    c1.wait()
    c1.close()
    assert t1.global_step == 1

    changed = make_sources(vocab=32) + [StreamSource(
        "extra", SyntheticLMDataset(size=16, seq_len=8, vocab_size=32,
                                    seed=5))]
    t2, c2 = _stream_trainer(cpu8, tmp_path, epochs=2, sources=changed)
    c2.close()
    assert int(t2.state["step"]) == 1        # optimizer state restored
    assert t2.epochs_run == 0                # replay epoch 0...
    assert t2.loader.state.step == 0         # ...from its start
    # The honest evidence: cursor (0) behind step * global_batch.
    assert t2.loader.state_dict()["samples_consumed"] == 0


# --- the acceptance e2e: mid-epoch preemption under --supervise -------------


_SOURCES = ("{wiki: {dataset: synthetic_lm, size: 48, seq_len: 12, "
            "vocab_size: 32, weight: 2.0}, "
            "docs: {dataset: synthetic_doc, size: 32, min_len: 4, "
            "max_len: 20, vocab_size: 32}}")


def _stream_overrides(out_dir, snap, **extra):
    over = {
        "run.output_dir": out_dir,
        "train.snapshot_path": snap,
        "train.total_epochs": 3,
        "train.batch_size": 4,
        "train.max_steps_per_epoch": 8,
        "train.pack_seq_len": 12,
        "train.log_every": 0,
        "train.save_every": 1,
        "train.collectives_audit": "false",
        "train.data_sources": _SOURCES,
        "model.vocab_size": 32,
        "model.d_model": 32,
        "model.n_layers": 1,
        "model.n_heads": 2,
        "model.max_seq_len": 16,
        "model.dtype": "float32",
    }
    over.update(extra)
    return ["model=byte_lm"] + [f"{k}={v}" for k, v in over.items()]


def _read_jsonl(path):
    from distributed_training_tpu.telemetry.summarize import load_jsonl
    return load_jsonl(path)


def test_supervised_mid_epoch_preemption_exactly_once_e2e(tmp_path):
    """ISSUE acceptance on CPU: a fault that lands MID-EPOCH under
    --supervise saves the StreamState cursor, the restart resumes from
    it (not the epoch start), and the finished run is bit-identical to
    an uninterrupted one — with the summarizer's recovery table
    proving 0 samples replayed / 0 skipped for the incident."""
    from distributed_training_tpu.checkpoint.export import (
        restore_step_local)
    from distributed_training_tpu.launch import local as launch_local_mod
    from distributed_training_tpu.telemetry.summarize import (
        render_recovery_lines, summarize_run)

    faulty = tmp_path / "faulty"
    # sigterm@10 = mid-epoch-1 (8 steps/epoch): the preemption-guard
    # save carries the cursor at step 10; the supervisor restarts and
    # the next incarnation must CONTINUE epoch 1 at step 10.
    rc = launch_local_mod.main([
        "--nproc", "1", "--devices-per-proc", "1",
        "--log-dir", str(faulty / "logs"),
        "--supervise", "--max-restarts", "2",
        "--backoff-base-s", "0.05",
        "--ckpt-dir", str(faulty / "ckpt"),
        "--", "-m", "distributed_training_tpu.train",
        *_stream_overrides(str(faulty / "out"), str(faulty / "ckpt")),
        "train.fault_plan=sigterm@10",
    ])
    assert rc == 0, "supervised run did not recover"

    run_dir = str(faulty / "out" / "default")
    events = _read_jsonl(os.path.join(run_dir, "events.jsonl"))
    resumes = [e for e in events if e.get("kind") == "resume"]
    assert len(resumes) == 1
    assert resumes[0]["step"] == 10          # mid-epoch, not 8
    assert resumes[0]["samples_consumed"] == 40  # 10 steps * gb 4
    assert resumes[0]["global_batch"] == 4
    assert resumes[0]["realized_mixture"]

    rec = summarize_run(run_dir)["recovery"]
    inc = rec["incidents"][0]
    assert inc["resumed_at_step"] == 10
    assert inc["steps_lost"] == 0            # clean preemption save
    assert inc["samples_replayed"] == 0
    assert inc["samples_skipped"] == 0
    assert "0 sample(s) replayed / 0 skipped" in "\n".join(
        render_recovery_lines(rec))

    # Uninterrupted reference with the same config and seed.
    clean = tmp_path / "clean"
    procs = launch_local_mod.launch_local(
        ["-m", "distributed_training_tpu.train",
         *_stream_overrides(str(clean / "out"), str(clean / "ckpt"))],
        num_processes=1, devices_per_process=1,
        log_dir=str(clean / "logs"))
    assert launch_local_mod.wait(procs, timeout=180) == 0

    got, got_step = restore_step_local(str(faulty / "ckpt"))
    want, want_step = restore_step_local(str(clean / "ckpt"))
    assert got_step == want_step == 24
    import jax
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        got["params"], want["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        got["opt_state"], want["opt_state"])
