"""Flash attention numerics vs the naive reference (interpret mode on
CPU; the same kernels compile on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.ops.attention import _naive_attention
from distributed_training_tpu.ops.flash_attention import (flash_attention,
                                                          supported)

# This container's pinned jax runs the Pallas kernels in interpret
# mode and the ring/pipeline numerics at minutes per test — far over
# the tier-1 wall-clock budget (the whole file was broken-at-import
# at seed, so the fast gate never paid for it). The fast gate still
# COMPILES these paths every run (the analysis SPMD audit target
# lowers ring attention under the full sharded train step; the
# test_benchmarks contract tests compile the strategy matrix); the
# kernel/numerics suites here run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def rand_qkv(B=1, S=256, H=2, D=32, Hkv=None, dtype=jnp.float32, seed=0):
    Hkv = Hkv or H
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_naive(causal):
    q, k, v = rand_qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = _naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_gqa():
    q, k, v = rand_qkv(H=4, Hkv=2)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_multi_block_seq():
    q, k, v = rand_qkv(S=512)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_naive(causal):
    q, k, v = rand_qkv(S=256, H=2, D=32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    def f_naive(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name} mismatch")


def test_bf16_forward_close():
    q, k, v = rand_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=3e-2, atol=3e-2)


def test_supported_gate(monkeypatch):
    import distributed_training_tpu.ops.flash_attention as fa
    q, k, v = rand_qkv(S=256)
    # Off-TPU, auto-dispatch must never choose the (interpreted) kernel.
    assert not supported(q, k, v)
    monkeypatch.setattr(fa, "_platform_is_tpu", lambda: True)
    assert fa.supported(q, k, v)
    q2, k2, v2 = rand_qkv(S=100)  # not block-divisible
    assert not fa.supported(q2, k2, v2)
    assert not fa.supported(q.astype(jnp.float16), k, v)
    # cross-length causal offset not implemented
    qs, _, _ = rand_qkv(S=128)
    assert not fa.supported(qs, k, v)


def test_wrapper_validation_errors():
    q, k, v = rand_qkv(S=256, H=4, Hkv=4)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=96)
    q6, k4, v4 = rand_qkv(S=256, H=6)[0], *rand_qkv(S=256, H=4)[1:]
    with pytest.raises(ValueError, match="n_heads"):
        flash_attention(q6, k4, v4)
    qs = rand_qkv(S=128)[0]
    with pytest.raises(ValueError, match="Sq == Sk"):
        flash_attention(qs, k, v, causal=True)


def test_dispatch_auto_uses_flash_on_tpu_and_matches(monkeypatch):
    from distributed_training_tpu.ops.attention import dot_product_attention
    q, k, v = rand_qkv(S=256)
    # On CPU "auto" resolves to naive; force the kernel (interpret mode)
    # to check dispatch equivalence.
    out_flash = dot_product_attention(q, k, v, causal=True, impl="flash")
    out_auto = dot_product_attention(q, k, v, causal=True, impl="auto")
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_flash),
                               rtol=2e-5, atol=2e-5)


def test_dispatch_auto_rejects_non_dividing_tile_override():
    """An explicit tile override that doesn't divide the sequence must
    raise under impl='auto', not silently measure the naive path under
    the override's label (ADVICE r3; mirrors ring's raise-don't-ignore)."""
    import pytest

    from distributed_training_tpu.ops.attention import dot_product_attention
    q, k, v = rand_qkv(S=256)
    with pytest.raises(ValueError, match="does not divide"):
        dot_product_attention(q, k, v, impl="auto", block_q=192)
    with pytest.raises(ValueError, match="does not divide"):
        dot_product_attention(q, k, v, impl="auto", block_k=96)
    # A dividing override stays legal.
    dot_product_attention(q, k, v, impl="auto", block_q=128, block_k=128)


def test_fused_bwd_matches_two_pass(monkeypatch):
    """The fused single-sweep backward (dq/dk/dv in one kernel, full
    (S, D) dq scratch) must produce the same gradients as the split
    FlashAttention-2 dq/dkv kernels it replaces on small-S shapes —
    including GQA group reduction and sliding windows. The split path
    is forced by shrinking the fused path's VMEM scratch budget."""
    from distributed_training_tpu.ops import flash_attention as fa

    def grads(**kw):
        q, k, v = rand_qkv(B=2, S=256, H=4, D=16, Hkv=2, seed=3)

        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64,
                **kw) ** 2)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for kw in ({}, {"window": 96}):
        # The total-residency gate (ADVICE r4) must keep this small
        # shape on the fused path, and zeroing the budget forces split.
        assert fa._fused_bwd_fits(256, 16, 64, 64, jnp.float32)
        fused = grads(**kw)
        monkeypatch.setattr(fa, "_FUSED_BWD_VMEM_LIMIT_BYTES", 0)
        split = grads(**kw)
        monkeypatch.undo()
        for a, b in zip(fused, split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)


def test_fused_bwd_vmem_gate_budgets_full_residency():
    """ADVICE r4 (medium): the fused-path gate must budget the softmax
    temporaries, dk/dv scratch, and double-buffered io tiles — not
    just the dq scratch. Pins the decision on the shapes that matter:
    the chip-proven headline stays fused; the S=8192 D=128 bf16 case
    that passed the old dq-only gate (6 MiB exactly) while its true
    residency exceeds VMEM now falls back to the split kernels."""
    from distributed_training_tpu.ops import flash_attention as fa

    # gpt2_125m headline: S=1024, D=64, seq-aware 1024x1024 tiles.
    assert fa._fused_bwd_fits(1024, 64, 1024, 1024, jnp.bfloat16)
    # The ADVICE overflow shape.
    assert not fa._fused_bwd_fits(8192, 128, 1024, 1024, jnp.bfloat16)
    # Ring callers (f32 grads) inflate dq residency ~1.5x.
    assert not fa._fused_bwd_fits(4096, 128, 1024, 1024, jnp.bfloat16,
                                  jnp.float32)
