"""Closed-loop diagnostics: the online anomaly detector, the incident
flight recorder, the auto-profile trigger, the offline doctor, and
their trainer wiring (slow_host / data_stall fault-plan e2e runs whose
--doctor verdicts must name the right limiter)."""

import json
import os

import pytest

from distributed_training_tpu import telemetry
from distributed_training_tpu.telemetry import anomaly as anomaly_mod
from distributed_training_tpu.telemetry import doctor as doctor_mod
from distributed_training_tpu.telemetry import incident as incident_mod
from distributed_training_tpu.telemetry.anomaly import (
    ANOMALY_KEYS, SIGNALS, AnomalyDetector, median_mad)
from distributed_training_tpu.telemetry.incident import (
    BUNDLE_CORE_FILES, IncidentRecorder, arm_autoprofile,
    is_incident_bundle, write_incident_bundle)


@pytest.fixture(autouse=True)
def _fresh_ambient():
    telemetry.uninstall()
    yield
    telemetry.uninstall()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _step(dur_s, step=0):
    return {"kind": "span", "name": "step", "dur_s": dur_s,
            "step": step}


def _emit_span(tel, name, dur_s, step=None):
    """Emit a span-close record with a CONTROLLED duration through
    the real sink (the span() context manager measures wall time, so
    tests that need exact durations inject the record directly)."""
    import time as _time
    rec = {"kind": "span", "name": name, "t": _time.time(),
           "dur_s": dur_s}
    if step is not None:
        rec["step"] = step
    tel._emit(rec)


def _feed_steps(det, durs, start_step=0):
    for i, d in enumerate(durs):
        det.observe(_step(d, step=start_step + i))


# -- schema pins -----------------------------------------------------------


def test_schema_pins():
    """The stable consumer surface: summarize/doctor/metrics_server
    and the bundle readers all key on these — additive changes only."""
    assert anomaly_mod.SCHEMA == 1
    assert incident_mod.SCHEMA == 1
    assert doctor_mod.SCHEMA == 1
    assert ANOMALY_KEYS == ("schema", "signal", "value", "median",
                            "mad", "deviation", "threshold", "step",
                            "window", "host", "detail")
    assert SIGNALS == ("step_time", "data_wait", "throughput",
                       "loss_nan", "loss_spike",
                       "serving_queue_depth", "serving_ttft")
    assert BUNDLE_CORE_FILES == ("meta.json", "stacks.txt",
                                 "events_tail.jsonl",
                                 "memory_stats.json")
    assert incident_mod.BUNDLE_OPTIONAL_FILES == (
        "anomaly.json", "attribution.json", "serving_requests.json")
    assert incident_mod.KINDS == ("anomaly", "watchdog", "preemption",
                                  "give_up", "manual", "engine_crash")
    assert doctor_mod.RULES == (
        "serving_engine_crash", "preemption_thrash",
        "data_skip_storm", "straggler", "serving_slo_breach",
        "input_bound", "exposed_comms", "compute_bound")


def test_median_mad():
    assert median_mad([]) == (0.0, 0.0)
    assert median_mad([3.0]) == (3.0, 0.0)
    assert median_mad([1.0, 2.0, 3.0]) == (2.0, 1.0)
    med, mad = median_mad([1.0, 2.0, 3.0, 4.0])
    assert med == pytest.approx(2.5) and mad == pytest.approx(1.0)
    # Robustness: one spike does not move the median baseline.
    med, _ = median_mad([1.0] * 9 + [100.0])
    assert med == pytest.approx(1.0)


# -- detector ---------------------------------------------------------------


def test_step_time_spike_fires_anomaly_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    det = AnomalyDetector(telemetry=tel, min_samples=8, threshold=8.0)
    tel.add_observer(det.observe)
    # Records come through the sink like the trainer's spans do.
    for i in range(12):
        _emit_span(tel, "step", 0.10 + 0.001 * (i % 3), step=i)
    _emit_span(tel, "step", 2.0, step=12)
    tel.close()
    anoms = [e for e in _read_jsonl(path) if e["kind"] == "anomaly"]
    assert len(anoms) == 1
    a = anoms[0]
    assert a["signal"] == "step_time" and a["step"] == 12
    assert a["value"] == pytest.approx(2.0)
    assert a["median"] == pytest.approx(0.101, abs=0.01)
    assert a["deviation"] > 8.0 and a["window"] == 12
    assert set(a) - {"t", "kind", "host"} <= set(ANOMALY_KEYS)
    assert det.anomalies_total == {"step_time": 1}


def test_quiet_window_needs_min_samples_and_floor():
    det = AnomalyDetector(min_samples=8, threshold=8.0)
    # Before min_samples nothing can fire, however extreme.
    _feed_steps(det, [0.1] * 7 + [50.0])
    assert det.anomalies_total == {}
    # A zero-variance window must not flag scheduler jitter: the
    # rel_floor turns a +20% blip into <= 4 "MADs".
    det2 = AnomalyDetector(min_samples=8, threshold=8.0)
    _feed_steps(det2, [0.1] * 16 + [0.12])
    assert det2.anomalies_total == {}


def test_cooldown_bounds_anomaly_storm():
    det = AnomalyDetector(min_samples=8, threshold=8.0, sustain=99)
    _feed_steps(det, [0.1] * 10)
    # 6 consecutive spikes: only the first fires (cooldown 8 obs),
    # though all count toward the sustain counter.
    _feed_steps(det, [5.0] * 6, start_step=10)
    assert det.anomalies_total == {"step_time": 1}
    assert det.state_fingerprint()["sustained_steps"] == 6


def test_loss_nan_spike_and_throughput_signals():
    det = AnomalyDetector(min_samples=4, threshold=8.0)
    for i in range(8):
        det.observe({"kind": "train_metrics", "step": i * 10,
                     "loss": 1.0 + 0.01 * i,
                     "samples_per_sec_per_chip": 100.0})
    # Low-side throughput collapse fires; loss stays quiet.
    det.observe({"kind": "train_metrics", "step": 80, "loss": 1.1,
                 "samples_per_sec_per_chip": 5.0})
    assert det.anomalies_total.get("throughput") == 1
    # Loss spike (high side).
    det.observe({"kind": "train_metrics", "step": 90, "loss": 50.0,
                 "samples_per_sec_per_chip": 100.0})
    assert det.anomalies_total.get("loss_spike") == 1
    # NaN loss was sanitized to null upstream -> loss_nan, detail set.
    det.observe({"kind": "train_metrics", "step": 100, "loss": None})
    assert det.anomalies_total.get("loss_nan") == 1
    assert det.verdict()["latest"]["loss_nan"]["detail"] == \
        "non-finite loss"
    # Warmup rows contribute no throughput sample.
    det2 = AnomalyDetector(min_samples=2)
    det2.observe({"kind": "train_metrics", "step": 0, "loss": 1.0,
                  "warmup": True})
    assert len(det2.state_fingerprint()["windows"]["throughput"]) == 0
    assert len(det2.state_fingerprint()["windows"]["loss_spike"]) == 1


def test_serving_signals():
    det = AnomalyDetector(min_samples=4, threshold=8.0)
    for _ in range(8):
        det.observe({"kind": "serving", "queue_depth": 2})
        det.observe({"kind": "serving_request", "ttft_s": 0.05})
    det.observe({"kind": "serving", "queue_depth": 500})
    det.observe({"kind": "serving_request", "ttft_s": 30.0})
    assert det.anomalies_total.get("serving_queue_depth") == 1
    assert det.anomalies_total.get("serving_ttft") == 1


def test_detector_ignores_own_output():
    det = AnomalyDetector(min_samples=2)
    for kind in anomaly_mod._SELF_KINDS:
        det.observe({"kind": kind, "signal": "step_time",
                     "value": 99.0})
    fp = det.state_fingerprint()
    assert all(not w for w in fp["windows"].values())


def test_replay_rebuilds_identical_state(tmp_path):
    """Restart determinism: the detector's whole state is a pure
    function of the event stream, so replaying the restored
    events.jsonl reproduces the live detector's fingerprint exactly —
    and emits nothing while doing it."""
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    live = AnomalyDetector(telemetry=tel, min_samples=8,
                           threshold=8.0, sustain=3)
    tel.add_observer(live.observe)
    for i in range(12):
        _emit_span(tel, "step", 0.1, step=i)
        _emit_span(tel, "data_wait", 0.01, step=i)
        tel.event("train_metrics", step=i, loss=1.0,
                  samples_per_sec_per_chip=100.0)
    for i in range(12, 17):
        _emit_span(tel, "step", 3.0, step=i)
    tel.close()
    events = _read_jsonl(path)  # includes the emitted anomaly rows

    replayed = AnomalyDetector(min_samples=8, threshold=8.0,
                               sustain=3)
    n = replayed.replay(events)
    assert n == len(events)
    assert replayed.state_fingerprint() == live.state_fingerprint()
    assert replayed.baselines() == live.baselines()
    # Replay emitted nothing and took no side-effecting action: the
    # sustained flag is rebuilt in memory, but no drop file appears
    # (run_dir unset) and no telemetry was attached to write to.
    assert replayed.state_fingerprint()["autoprofile_armed"]


def test_baseline_events_on_cadence(tmp_path):
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    det = AnomalyDetector(telemetry=tel, min_samples=4,
                          baseline_every=10)
    tel.add_observer(det.observe)
    for i in range(25):
        _emit_span(tel, "step", 0.1, step=i)
    tel.close()
    snaps = [e for e in _read_jsonl(path)
             if e["kind"] == "anomaly_baseline"]
    assert len(snaps) == 2  # steps 10 and 20
    assert snaps[0]["step_time_s"] == pytest.approx(0.1)
    assert set(snaps[0]) - {"t", "kind", "host"} <= \
        set(anomaly_mod.BASELINE_KEYS)


# -- auto-profile arming ----------------------------------------------------


def test_arm_autoprofile_ledger_before_action(tmp_path):
    run_dir = str(tmp_path)
    assert arm_autoprofile(run_dir, key="step_time_sustained",
                           evidence={"deviation": 12.0})
    ledger = os.path.join(run_dir, "incidents",
                          incident_mod.AUTOPROFILE_LEDGER)
    trigger = os.path.join(run_dir, "profile_now")
    assert os.path.exists(ledger) and os.path.exists(trigger)
    with open(ledger) as f:
        fired = json.load(f)
    assert fired["step_time_sustained"]["evidence"]["deviation"] == 12.0
    # One-shot: the ledger survives even after ProfileCapture consumed
    # the drop file, so a restarted incarnation cannot re-arm.
    os.remove(trigger)
    assert not arm_autoprofile(run_dir, key="step_time_sustained")
    assert not os.path.exists(trigger)
    # A different key is a different decision.
    assert arm_autoprofile(run_dir, key="other")


def test_sustained_regression_arms_profile_once(tmp_path):
    run_dir = str(tmp_path)
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    det = AnomalyDetector(telemetry=tel, run_dir=run_dir,
                          min_samples=8, threshold=8.0, sustain=3)
    tel.add_observer(det.observe)
    for i in range(10):
        _emit_span(tel, "step", 0.1, step=i)
    for i in range(10, 20):
        _emit_span(tel, "step", 4.0, step=i)
    tel.close()
    assert os.path.exists(os.path.join(run_dir, "profile_now"))
    armed = [e for e in _read_jsonl(path)
             if e["kind"] == "anomaly" and "profile capture armed"
             in str(e.get("detail"))]
    assert len(armed) == 1  # one-shot despite 10 slow steps


# -- incident bundles -------------------------------------------------------


def test_write_incident_bundle_atomic_and_complete(tmp_path):
    base = str(tmp_path / "incidents")
    path = write_incident_bundle(
        base, reason="unit test", kind="manual",
        events_tail=[{"kind": "span", "name": "step", "dur_s": 1.0}],
        extra={"note": 7},
        anomaly={"anomalies_total": {"step_time": 2}},
        attribution={"kind": "attribution", "host_frac": 0.1},
        serving={"in_flight": 0, "queue_depth": 0, "requests": []})
    assert os.path.isdir(path) and is_incident_bundle(path)
    names = set(os.listdir(path))
    assert set(BUNDLE_CORE_FILES) <= names
    assert set(incident_mod.BUNDLE_OPTIONAL_FILES) <= names
    # Atomic publish: no half-written .tmp turd remains.
    assert not any(n.endswith(".tmp") for n in os.listdir(base))
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["schema"] == 1 and meta["kind"] == "manual"
    assert meta["reason"] == "unit test" and meta["note"] == 7
    tail = _read_jsonl(os.path.join(path, "events_tail.jsonl"))
    assert tail[0]["name"] == "step"
    # Two bundles in the same second land in distinct directories.
    path2 = write_incident_bundle(base, reason="again")
    assert path2 != path and os.path.isdir(path2)


def test_watchdog_postmortem_is_an_incident_bundle(tmp_path):
    """Satellite: ONE postmortem artifact. write_postmortem delegates
    to the bundle writer, so its directories carry the bundle schema
    (additive on the legacy layout the watchdog tests pin)."""
    from distributed_training_tpu.telemetry.watchdog import (
        write_postmortem)
    path = write_postmortem(str(tmp_path / "postmortem"),
                            reason="hang at step 5",
                            events_tail=[{"kind": "span"}],
                            extra={"step": 5})
    assert is_incident_bundle(path)
    assert set(BUNDLE_CORE_FILES) <= set(os.listdir(path))
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["schema"] == 1 and meta["kind"] == "watchdog"
    assert meta["step"] == 5


def test_recorder_bundles_anomaly_with_verdict_and_serving(tmp_path):
    run_dir = str(tmp_path)
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    det = AnomalyDetector(telemetry=tel, min_samples=8,
                          threshold=8.0)
    rec = IncidentRecorder(
        run_dir, telemetry=tel, detector=det,
        serving_snapshot=lambda: {"in_flight": 1, "queue_depth": 3,
                                  "requests": [{"id": "r1"}]},
        cooldown_s=60.0)
    tel.add_observer(det.observe)
    tel.add_observer(rec.observe)
    tel.event("attribution", host_frac=0.2, collective_frac=0.1)
    for i in range(12):
        _emit_span(tel, "step", 0.1, step=i)
    _emit_span(tel, "step", 5.0, step=12)
    _emit_span(tel, "step", 5.0, step=13)
    tel.close()
    inc_dir = os.path.join(run_dir, "incidents")
    bundles = [d for d in os.listdir(inc_dir)
               if os.path.isdir(os.path.join(inc_dir, d))]
    assert len(bundles) == 1  # cooldown swallowed the second anomaly
    b = os.path.join(inc_dir, bundles[0])
    with open(os.path.join(b, "meta.json")) as f:
        meta = json.load(f)
    assert meta["kind"] == "anomaly" and meta["incident_seq"] == 1
    assert meta["trigger"]["signal"] == "step_time"
    with open(os.path.join(b, "anomaly.json")) as f:
        verdict = json.load(f)
    assert verdict["anomalies_total"]["step_time"] >= 1
    with open(os.path.join(b, "serving_requests.json")) as f:
        assert json.load(f)["queue_depth"] == 3
    with open(os.path.join(b, "attribution.json")) as f:
        assert json.load(f)["host_frac"] == 0.2
    # The flight-recorder tail made it into the bundle, and the
    # incident itself went back onto the stream for the summarizer.
    tail = _read_jsonl(os.path.join(b, "events_tail.jsonl"))
    assert any(e.get("kind") == "span" for e in tail)
    incidents = [e for e in _read_jsonl(path)
                 if e["kind"] == "incident"]
    assert len(incidents) == 1
    assert incidents[0]["incident_kind"] == "anomaly"
    assert bundles[0] in incidents[0]["path"]


def test_recorder_watchdog_and_give_up_triggers(tmp_path):
    run_dir = str(tmp_path)
    tel = telemetry.Telemetry(
        events_jsonl=str(tmp_path / "events.jsonl"))
    rec = IncidentRecorder(run_dir, telemetry=tel, cooldown_s=0.0)
    tel.add_observer(rec.observe)
    tel.event("watchdog_fired", reason="no step for 60s",
              postmortem="postmortem/x")
    tel.event("supervisor_give_up", outcome="crash", returncode=1)
    tel.close()
    inc_dir = os.path.join(run_dir, "incidents")
    kinds = set()
    for d in sorted(os.listdir(inc_dir)):
        with open(os.path.join(inc_dir, d, "meta.json")) as f:
            kinds.add(json.load(f)["kind"])
    assert kinds == {"watchdog", "give_up"}


def test_recorder_cap_and_disable(tmp_path):
    rec = IncidentRecorder(str(tmp_path), cooldown_s=0.0,
                           max_bundles=2)
    assert rec.record("manual", reason="a")
    assert rec.record("manual", reason="b")
    assert rec.record("manual", reason="c") is None  # hard cap
    off = IncidentRecorder(str(tmp_path / "off"), enabled=False)
    assert off.record("manual", reason="x") is None
    assert not os.path.exists(str(tmp_path / "off"))


# -- doctor -----------------------------------------------------------------


def _anom(signal, step, value=2.0, median=0.1, host=None):
    a = {"kind": "anomaly", "schema": 1, "signal": signal,
         "step": step, "value": value, "median": median,
         "mad": 0.001, "deviation": 25.0, "threshold": 8.0,
         "window": 32}
    if host is not None:
        a["host"] = host
    return a


def test_doctor_compute_bound_fallback():
    report = doctor_mod.diagnose(
        [{"kind": "span", "name": "step", "dur_s": 0.1, "step": i}
         for i in range(5)])
    assert report["verdict"] == "compute_bound"
    assert report["findings"][0]["evidence"]


def test_doctor_straggler_names_the_host():
    events = [{"kind": "fault_injected",
               "fault": "slow_host@10:host=2", "step": 10},
              _anom("step_time", 11, host=2),
              _anom("step_time", 12, host=2)]
    report = doctor_mod.diagnose(events)
    assert report["verdict"] == "straggler"
    assert "host 2" in report["findings"][0]["summary"]
    assert any("anomaly at step" in ln
               for ln in report["findings"][0]["evidence"])
    assert report["anomalies"]["step_time"] == 2


def test_doctor_input_bound_from_data_faults():
    events = [{"kind": "fault_injected", "fault": "data_stall@6",
               "step": 6},
              _anom("data_wait", 6, value=0.5, median=0.01)]
    report = doctor_mod.diagnose(events)
    assert report["verdict"] == "input_bound"
    ev = "\n".join(report["findings"][0]["evidence"])
    assert "data_stall@6" in ev and "anomaly at step 6" in ev


def test_doctor_preemption_thrash_beats_input_bound():
    # Recovery incidents are segment boundaries: each restart appends
    # a run_start marker + a resume event (summarize._recovery).
    events = [{"kind": "run_start", "t": 0.0, "step": 0},
              {"kind": "span", "name": "step", "t": 1.0, "dur_s": 0.1,
               "step": 4}]
    for i in range(doctor_mod.THRASH_RESTARTS):
        t0 = 10.0 * (i + 1)
        events.append({"kind": "run_start", "t": t0, "step": 2})
        events.append({"kind": "resume", "t": t0 + 0.1, "step": 2,
                       "restarts": i + 1})
        events.append({"kind": "span", "name": "step", "t": t0 + 1,
                       "dur_s": 0.1, "step": 4})
    events += [_anom("data_wait", 5), _anom("data_wait", 6)]
    report = doctor_mod.diagnose(events)
    assert report["verdict"] == "preemption_thrash"
    rules = [f["rule"] for f in report["findings"]]
    assert "input_bound" in rules  # secondary finding, still cited


def test_doctor_exposed_comms():
    report = doctor_mod.diagnose(
        [{"kind": "attribution", "step": 50, "compute_frac": 0.5,
          "collective_frac": 0.45, "host_frac": 0.05,
          "overlap_frac": 0.1}])
    assert report["verdict"] == "exposed_comms"


def test_doctor_reads_incident_bundle(tmp_path):
    path = write_incident_bundle(
        str(tmp_path / "incidents"), reason="anomaly storm",
        kind="anomaly",
        events_tail=[_anom("step_time", 40, host=1),
                     {"kind": "fault_injected",
                      "fault": "slow_host@30:host=1", "step": 30}],
        anomaly={"schema": 1,
                 "anomalies_total": {"step_time": 7},
                 "latest": {}, "baselines": {}})
    report = doctor_mod.diagnose_path(path)
    assert report["source"] == "bundle"
    assert report["incident"]["kind"] == "anomaly"
    assert report["verdict"] == "straggler"
    # The bundle's recorded totals extend the truncated tail's view.
    assert report["anomalies"]["step_time"] == 7
    text = doctor_mod.render_doctor(report)
    assert "VERDICT: straggler" in text
    assert "incident bundle: kind=anomaly" in text


def test_doctor_cli(tmp_path, capsys):
    from distributed_training_tpu.telemetry.summarize import main
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with open(run_dir / "events.jsonl", "w") as f:
        for i in range(5):
            f.write(json.dumps({"kind": "span", "name": "step",
                                "dur_s": 0.1, "step": i}) + "\n")
        f.write(json.dumps(_anom("data_wait", 3)) + "\n")
        f.write(json.dumps(_anom("data_wait", 4)) + "\n")
    assert main([str(run_dir), "--doctor"]) == 0
    out = capsys.readouterr().out
    assert "VERDICT: input_bound" in out
    assert main([str(run_dir), "--doctor", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == "input_bound"
    assert report["anomalies"] == {"data_wait": 2}


# -- metrics endpoint -------------------------------------------------------


def test_metrics_server_anomaly_counters_and_gauges():
    from distributed_training_tpu.telemetry.metrics_server import (
        MetricsServer)
    ms = MetricsServer(0)
    ms.observe(_anom("step_time", 10))
    ms.observe(_anom("step_time", 20))
    ms.observe(_anom("data_wait", 30))
    ms.observe({"kind": "anomaly_baseline", "step": 50,
                "step_time_s": 0.123, "data_wait_s": 0.004})
    ms.observe({"kind": "incident", "schema": 1, "kind2": "x"})
    body = ms.render()
    assert 'dtt_anomalies_total{kind="step_time"} 2' in body
    assert 'dtt_anomalies_total{kind="data_wait"} 1' in body
    assert "# TYPE dtt_anomalies_total counter" in body
    assert "dtt_incidents_total 1" in body
    assert "dtt_anomaly_baseline_step_time_s 0.123" in body
    assert "dtt_anomaly_baseline_data_wait_s 0.004" in body


# -- trainer e2e: fault plans -> incident bundles -> doctor verdicts -------


def _e2e_run(tmp_path, name, fault_plan, **overrides):
    from distributed_training_tpu.train import cli as train_cli
    out = tmp_path / name
    args = {
        "train.total_epochs": 3,
        "train.dataset_size": 96,
        "train.global_batch_size": 8,  # 12 steps/epoch on 8 shards
        "train.log_every": 2,
        "train.save_every": 0,
        "train.hbm_sample_every": 0,
        "train.anomaly_window": 16,
        "train.anomaly_min_samples": 6,
        "train.anomaly_threshold": 8.0,
        "train.anomaly_sustain": 3,
        "run.output_dir": str(out),
        "train.fault_plan": fault_plan,
    }
    args.update(overrides)
    rc = train_cli.main([f"{k}={v}" for k, v in args.items()])
    assert rc == 0
    return str(out / "default")


def _bundle_dirs(run_dir):
    inc = os.path.join(run_dir, "incidents")
    if not os.path.isdir(inc):
        return []
    return sorted(os.path.join(inc, d) for d in os.listdir(inc)
                  if os.path.isdir(os.path.join(inc, d)))


def test_slow_host_e2e_incident_and_straggler_verdict(tmp_path):
    """ISSUE acceptance: an injected slow_host plan produces an
    incident bundle and a --doctor verdict that names the straggler,
    and the sustained regression arms the in-run profile capture via
    the profile_now drop file (one-shot, ledgered)."""
    run_dir = _e2e_run(tmp_path, "slow",
                       fault_plan="slow_host@20:host=0:300ms")
    events = _read_jsonl(os.path.join(run_dir, "events.jsonl"))
    anoms = [e for e in events if e["kind"] == "anomaly"
             and e.get("signal") == "step_time"]
    assert anoms, "detector missed a 300ms stall on every step"
    assert anoms[0]["deviation"] > 8.0

    bundles = _bundle_dirs(run_dir)
    assert bundles, "no incident bundle written"
    assert is_incident_bundle(bundles[0])
    with open(os.path.join(bundles[0], "meta.json")) as f:
        assert json.load(f)["kind"] == "anomaly"

    # Closed loop: sustained regression armed the profile capture.
    ledger = os.path.join(run_dir, "incidents",
                          incident_mod.AUTOPROFILE_LEDGER)
    assert os.path.exists(ledger)
    with open(ledger) as f:
        assert "step_time_sustained" in json.load(f)

    report = doctor_mod.diagnose_path(run_dir)
    assert report["verdict"] == "straggler"
    ev = "\n".join(report["findings"][0]["evidence"])
    assert "slow_host@20:host=0" in ev


def test_data_stall_e2e_incident_and_input_bound_verdict(tmp_path):
    """ISSUE acceptance: an injected data_stall plan produces an
    incident bundle and an input-bound --doctor verdict citing the
    data_wait anomalies."""
    run_dir = _e2e_run(
        tmp_path, "stall",
        fault_plan="data_stall@15:400ms,data_stall@20:400ms,"
                   "data_stall@25:400ms")
    events = _read_jsonl(os.path.join(run_dir, "events.jsonl"))
    anoms = [e for e in events if e["kind"] == "anomaly"
             and e.get("signal") == "data_wait"]
    assert anoms, "detector missed a 400ms data stall"
    bundles = _bundle_dirs(run_dir)
    assert bundles and is_incident_bundle(bundles[0])
    report = doctor_mod.diagnose_path(run_dir)
    assert report["verdict"] == "input_bound"
    ev = "\n".join(report["findings"][0]["evidence"])
    assert "data_stall@15" in ev
