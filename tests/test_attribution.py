"""Step-time attribution subsystem (telemetry/xplane.py,
attribution.py, metrics_server.py): XSpace encode/parse round trip,
timeline attribution arithmetic pinned to exact fractions on
synthesized device lanes, the host-fallback executor-window filter,
static schedule-overlap scoring on hand-written HLO, the
OVERLAP_baseline ratchet (pin-outranks-baseline included), in-run
ProfileCapture (one-shot ledger, drop-file trigger), the live
Prometheus endpoint + /healthz, and the CPU trainer end-to-end
(`attribution` + `attribution_static` events). All tier-1-safe, zero
devices beyond the faked CPU mesh."""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_training_tpu import telemetry
from distributed_training_tpu.analysis import baseline
from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models import build_model
from distributed_training_tpu.telemetry import attribution, xplane
from distributed_training_tpu.telemetry.attribution import (
    ProfileCapture, hlo_overlap_report, parse_profile_at)
from distributed_training_tpu.telemetry.metrics_server import (
    MetricsServer)
from distributed_training_tpu.train.trainer import Trainer


@pytest.fixture(autouse=True)
def _fresh_ambient():
    telemetry.uninstall()
    yield
    telemetry.uninstall()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _ev(name, start, dur):
    return xplane.Event(name=name, start_ps=start, dur_ps=dur)


# -- xplane wire format ----------------------------------------------------


def test_xspace_encode_parse_round_trip():
    planes = [xplane.Plane(name="/device:TPU:0", lanes=[
        xplane.Lane(name="XLA Ops", events=[
            _ev("fusion.1", 0, 10), _ev("all-gather.2", 5, 10)]),
        xplane.Lane(name="Steps", events=[_ev("step 3", 0, 15)]),
    ])]
    back = xplane.parse_xspace(xplane.encode_xspace(planes))
    assert len(back) == 1 and back[0].name == "/device:TPU:0"
    assert [ln.name for ln in back[0].lanes] == ["XLA Ops", "Steps"]
    evs = back[0].lanes[0].events
    assert [(e.name, e.start_ps, e.dur_ps) for e in evs] == \
        [("fusion.1", 0, 10), ("all-gather.2", 5, 10)]


def test_parse_rejects_garbage():
    with pytest.raises(xplane.XplaneError):
        # wire type 7 does not exist
        xplane.parse_xspace(bytes([0x0F, 0x01]))


def test_load_xspace_converts_any_corruption_to_typed_error(
        tmp_path):
    """A truncated/corrupt trace must surface as XplaneError — the
    runtime consumer catches exactly that type, and an arbitrary
    parse exception would propagate into the step loop."""
    good = xplane.encode_xspace([xplane.Plane(
        name="/device:TPU:0", lanes=[xplane.Lane(
            name="XLA Ops", events=[_ev("fusion.1", 0, 10)])])])
    for i, blob in enumerate((good[:-3], b"\x00\x01junk",
                              good + b"\x0f")):
        p = tmp_path / f"bad{i}.xplane.pb"
        p.write_bytes(blob)
        with pytest.raises(xplane.XplaneError):
            xplane.load_xspace(str(p))


# -- attribution arithmetic ------------------------------------------------


def test_attribution_exact_fractions_on_device_lane():
    """Known intervals → exact expected fractions. One lane:
    compute [0,10) + [20,25), collective [5,15). Window 25: compute
    15/25, exposed collective 5/25, host 5/25, overlap 5/10."""
    planes = [xplane.Plane(name="/device:TPU:0", lanes=[
        xplane.Lane(name="XLA Ops", events=[
            _ev("fusion.1", 0, 10),
            _ev("all-gather-start.2", 5, 10),
            _ev("fusion.3", 20, 5)])])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["source"] == "device"
    assert rep["compute_frac"] == 0.6
    assert rep["collective_frac"] == 0.2
    assert rep["host_frac"] == 0.2
    assert rep["overlap_frac"] == 0.5
    assert rep["compute_frac"] + rep["collective_frac"] \
        + rep["host_frac"] == pytest.approx(1.0)


def test_attribution_cross_lane_overlap_counts_once():
    """A collective on its own lane fully under compute on another:
    overlap 100%, zero exposed collective; concurrent compute on two
    lanes is unioned, not summed."""
    planes = [xplane.Plane(name="/device:TPU:0", lanes=[
        xplane.Lane(name="XLA Ops", events=[
            _ev("fusion.1", 0, 20), _ev("fusion.2", 10, 20)]),
        xplane.Lane(name="XLA Ops", events=[
            _ev("all-reduce.9", 5, 10)])])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["overlap_frac"] == 1.0
    assert rep["collective_frac"] == 0.0
    assert rep["compute_frac"] == 1.0  # [0,30) covers the window
    assert rep["host_frac"] == 0.0


def test_attribution_device_plane_prefers_xla_ops_lane():
    """With an "XLA Ops" lane present, coarser lanes ("Steps", "XLA
    Modules") must not double-count the same wall-clock."""
    planes = [xplane.Plane(name="/device:TPU:0", lanes=[
        xplane.Lane(name="Steps", events=[_ev("step 1", 0, 100)]),
        xplane.Lane(name="XLA Ops", events=[_ev("fusion.1", 0, 10)]),
    ])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["events"] == 1 and rep["compute_frac"] == 1.0


def test_attribution_host_fallback_uses_executor_windows():
    """CPU-platform shape: ops execute inline on the python lane
    inside executor windows. Python frames ($-prefixed), telemetry
    span annotations (straddle the window), and the executor records
    themselves are all excluded as ops — but the step annotation
    WIDENS the window, so the data wait before the first op counts
    as host time instead of silently falling outside."""
    planes = [xplane.Plane(name="/host:CPU", lanes=[
        xplane.Lane(name="python", events=[
            _ev("$builtins isinstance", 0, 30),
            _ev("step", 0, 30),  # telemetry TraceAnnotation
            _ev("TfrtCpuExecutable::Execute", 10, 10),
            _ev("dot.1", 12, 4)]),
        xplane.Lane(name="tf_XLAEigen/1", events=[
            _ev("fusion.2", 14, 4)])])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["source"] == "host"
    assert rep["events"] == 2
    # compute union [12,18) over the annotation window [0,30).
    assert rep["compute_frac"] == 0.2 and rep["host_frac"] == 0.8


def test_attribution_window_widened_by_step_annotation():
    """An input-bound step (ops clustered at the end of a long
    data_wait) must attribute the wait to host+data — without the
    annotation widening, the window would clip to the ops alone and
    report host_frac 0 on exactly the run attribution exists to
    diagnose."""
    u = 10 ** 7  # ps per fixture tick, so window_s survives rounding
    planes = [xplane.Plane(name="/host:CPU", lanes=[
        xplane.Lane(name="python", events=[
            _ev("data_wait", 0, 80 * u),
            _ev("step", 80 * u, 20 * u),
            _ev("dot.1", 90 * u, 10 * u)])])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["window_s"] == pytest.approx(100 * u * 1e-12)
    assert rep["compute_frac"] == 0.1
    assert rep["host_frac"] == 0.9
    # Fixtures without annotations keep the op-extent window.
    no_marker = [xplane.Plane(name="/host:CPU", lanes=[
        xplane.Lane(name="w", events=[_ev("dot.1", 90 * u,
                                          10 * u)])])]
    assert xplane.attribution_of_planes(no_marker)["host_frac"] == 0.0


def test_attribution_host_fallback_without_executor_windows():
    """A vintage with no recognizable executor records keeps every
    classifiable event (best-effort beats silence)."""
    planes = [xplane.Plane(name="/host:CPU", lanes=[
        xplane.Lane(name="worker", events=[_ev("dot.5", 0, 10)])])]
    rep = xplane.attribution_of_planes(planes)
    assert rep["events"] == 1 and rep["compute_frac"] == 1.0


def test_attribution_empty_trace():
    rep = xplane.attribution_of_planes(
        [xplane.Plane(name="/host:CPU", lanes=[])])
    assert rep["host_frac"] == 1.0 and rep["events"] == 0
    assert rep["compute_frac"] + rep["collective_frac"] \
        + rep["host_frac"] == pytest.approx(1.0)


def test_classify_event():
    assert xplane.classify_event("all-reduce.5") == "collective"
    assert xplane.classify_event("reduce-scatter-start.1") == \
        "collective"
    assert xplane.classify_event("collective-permute.2") == \
        "collective"
    assert xplane.classify_event("reduce.8") == "compute"  # not AR
    assert xplane.classify_event("fusion.3") == "compute"
    assert xplane.classify_event("$abc.py:1 frame") is None
    assert xplane.classify_event("ThreadpoolListener::Record") is None
    assert xplane.classify_event("") is None


# -- static schedule-overlap audit ----------------------------------------


_ASYNC_SEPARATED = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %ag-start = f32[16,8]{1,0} all-gather-start(f32[8,8]{1,0} %p0), dimensions={0}
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
  %fusion.2 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %dot.1), kind=kLoop
  %ag-done = f32[16,8]{1,0} all-gather-done(f32[16,8]{1,0} %ag-start)
  ROOT %add = f32[8,8]{1,0} add(f32[8,8]{1,0} %fusion.2, f32[8,8]{1,0} %fusion.2)
}
"""

_ASYNC_ADJACENT = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
  %ag-start = f32[16,8]{1,0} all-gather-start(f32[8,8]{1,0} %p0), dimensions={0}
  %ag-done = f32[16,8]{1,0} all-gather-done(f32[16,8]{1,0} %ag-start)
  ROOT %add = f32[8,8]{1,0} add(f32[8,8]{1,0} %dot.1, f32[8,8]{1,0} %dot.1)
}
"""


def test_overlap_async_pair_with_separation_scores_one():
    rep = hlo_overlap_report(_ASYNC_SEPARATED)
    assert rep["scored"] == 1 and rep["async_pairs"] == 1
    assert rep["overlap_score"] == 1.0
    assert rep["pairs"][0]["compute_between"] == 2
    assert rep["pairs"][0]["kind"] == "all-gather"


def test_overlap_async_pair_adjacent_scores_zero():
    rep = hlo_overlap_report(_ASYNC_ADJACENT)
    assert rep["scored"] == 1
    assert rep["overlap_score"] == 0.0
    assert rep["pairs"][0]["compute_between"] == 0


def test_overlap_sync_form_scheduled_uses_first_consumer():
    text = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %ag.9 = f32[16,8]{1,0} all-gather(f32[8,8]{1,0} %p0), dimensions={0}
  %ag.90 = f32[16,8]{1,0} all-gather(f32[8,8]{1,0} %p0), dimensions={0}
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
  %use.90 = f32[16,8]{1,0} negate(f32[16,8]{1,0} %ag.90)
  ROOT %use.9 = f32[16,8]{1,0} add(f32[16,8]{1,0} %ag.9, f32[16,8]{1,0} %ag.9)
}
"""
    rep = hlo_overlap_report(text)
    # %ag.9's first use is AFTER the dot (overlapped); %ag.90's gap
    # holds the same dot — and the consumer match must be exact
    # (%ag.9 must not match %ag.90's use).
    assert rep["scored"] == 2
    assert rep["overlap_score"] == 1.0


def test_overlap_sync_form_unscheduled_not_scored():
    text = _ASYNC_SEPARATED.replace(", is_scheduled=true", "")
    text = text.replace("all-gather-start", "all-gather").replace(
        "all-gather-done(f32[16,8]{1,0} %ag-start)",
        "negate(f32[16,8]{1,0} %ag-start)")
    rep = hlo_overlap_report(text)
    assert rep["scored"] == 0 and rep["overlap_score"] is None
    assert rep["unscored"] >= 1


def test_overlap_tuple_typed_collectives_are_scored():
    """Async starts and combiner-grouped all-reduces have TUPLE
    result types with spaces — the instruction parser must not drop
    them, or enabling async collectives would make them vanish from
    the score instead of raising it."""
    text = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %ags = (f32[8,8]{1,0}, f32[16,8]{1,0}) all-gather-start(f32[8,8]{1,0} %p0), dimensions={0}
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
  %agd = f32[16,8]{1,0} all-gather-done((f32[8,8]{1,0}, f32[16,8]{1,0}) %ags)
  %car = (f32[8,8]{1,0}, f32[8,8]{1,0}) all-reduce(f32[8,8]{1,0} %dot.1, f32[8,8]{1,0} %p0)
  %fusion.9 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %dot.1), kind=kLoop
  ROOT %gte = f32[8,8]{1,0} get-tuple-element((f32[8,8]{1,0}, f32[8,8]{1,0}) %car), index=0
}
"""
    rep = hlo_overlap_report(text)
    assert rep["scored"] == 2, rep
    kinds = sorted(p["kind"] for p in rep["pairs"])
    assert kinds == ["all-gather", "all-reduce"]
    assert rep["overlap_score"] == 1.0  # dot / fusion in both gaps


def test_overlap_nested_tuple_async_start_is_scored():
    """A combiner-grouped async start over 2 operands has a
    tuple-of-tuples result type — still one scored collective."""
    tt = "((f32[8]{0}, f32[8]{0}), (f32[16]{0}, f32[16]{0}))"
    text = f"""HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8]) -> f32[8] {{
  %p0 = f32[8]{{0}} parameter(0)
  %ags = {tt} all-gather-start(f32[8]{{0}} %p0, f32[8]{{0}} %p0)
  %dot.1 = f32[8]{{0}} dot(f32[8]{{0}} %p0, f32[8]{{0}} %p0)
  ROOT %agd = (f32[16]{{0}}, f32[16]{{0}}) all-gather-done({tt} %ags)
}}
"""
    rep = hlo_overlap_report(text)
    assert rep["scored"] == 1 and rep["async_pairs"] == 1
    assert rep["overlap_score"] == 1.0


def test_overlap_fused_rs_is_not_compute_in_anothers_gap():
    """Two back-to-back fused reduce-scatters must not score each
    other as hidden compute — a pure-comms gap is exposed."""
    text = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %rs.1 = f32[1,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kCustom, calls=%all-reduce-scatter.2
  %rs.3 = f32[1,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kCustom, calls=%all-reduce-scatter.4
  %use.1 = f32[1,8]{1,0} negate(f32[1,8]{1,0} %rs.1)
  ROOT %use.3 = f32[1,8]{1,0} add(f32[1,8]{1,0} %rs.3, f32[1,8]{1,0} %rs.3)
}
"""  # noqa: E501 — verbatim HLO line shapes
    rep = hlo_overlap_report(text)
    assert rep["scored"] == 2
    assert rep["overlap_score"] == 0.0, rep["pairs"]


def test_overlap_fused_reduce_scatter_counts_as_collective():
    text = """HloModule t, is_scheduled=true

ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %rs.1 = f32[1,8]{1,0} fusion(f32[8,8]{1,0} %p0), kind=kCustom, calls=%all-reduce-scatter.2
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p0)
  ROOT %use = f32[1,8]{1,0} negate(f32[1,8]{1,0} %rs.1)
}
"""
    rep = hlo_overlap_report(text)
    assert rep["scored"] == 1
    assert rep["pairs"][0]["kind"] == "reduce-scatter"
    assert rep["overlap_score"] == 1.0


# -- OVERLAP_baseline ratchet ---------------------------------------------


def _doc(score, scored=10, target="t1"):
    return {"targets": [{"target": target,
                         "overlap": {"overlap_score": score,
                                     "scored": scored}}]}


def test_overlap_ratchet_pass_and_regress(tmp_path):
    path = str(tmp_path / "OVERLAP_baseline.json")
    baseline.write_overlap(_doc(0.3), path=path)
    base = baseline.load_overlap(path)
    assert base["targets"]["t1"]["overlap_score"] == 0.3
    # same and better pass; worse fails; evidence vanishing fails.
    assert baseline.compare_overlap(_doc(0.3), base) == []
    assert baseline.compare_overlap(_doc(0.4), base) == []
    assert baseline.compare_overlap(_doc(0.2), base)
    assert baseline.compare_overlap(_doc(None, scored=0), base)


def test_overlap_ratchet_ungated_until_baselined():
    empty = {"schema": 1, "targets": {}}
    assert baseline.compare_overlap(_doc(0.01), empty) == []


def test_overlap_pin_outranks_baseline(tmp_path):
    """A min_overlap pin fails a low score even when the committed
    baseline was (wrongly) rewritten below it, and --write-baseline
    refuses to freeze a sub-pin score at all."""
    path = str(tmp_path / "OVERLAP_baseline.json")
    # Baseline laundered down to 0.1: the ratchet alone would pass...
    baseline.write_overlap(_doc(0.1), path=path)
    base = baseline.load_overlap(path)
    assert baseline.compare_overlap(_doc(0.1), base) == []
    # ...but the pin still fails it.
    problems = baseline.compare_overlap(_doc(0.1), base,
                                        min_overlap={"t1": 0.25})
    assert problems and "min_overlap pin" in problems[0]
    with pytest.raises(ValueError):
        baseline.write_overlap(_doc(0.1), path=path,
                               min_overlap={"t1": 0.25})


def test_write_baseline_refuses_to_lower_raised_floor(tmp_path):
    """The ratchet only tightens by default: a regressed score (or
    vanished evidence) cannot ride a routine --write-baseline into a
    lower committed floor; an intentional slackening passes
    allow_lower explicitly and still cannot cross a pin."""
    path = str(tmp_path / "OVERLAP_baseline.json")
    baseline.write_overlap(_doc(0.9), path=path)
    with pytest.raises(ValueError, match="LOWER"):
        baseline.write_overlap(_doc(0.5), path=path)
    with pytest.raises(ValueError, match="LOWER"):
        baseline.write_overlap(_doc(None, scored=0), path=path)
    # The refusals left the committed floor untouched.
    assert baseline.load_overlap(path)["targets"]["t1"][
        "overlap_score"] == 0.9
    baseline.write_overlap(_doc(0.5), path=path, allow_lower=True)
    assert baseline.load_overlap(path)["targets"]["t1"][
        "overlap_score"] == 0.5
    with pytest.raises(ValueError, match="min_overlap pin"):
        baseline.write_overlap(_doc(0.1), path=path,
                               allow_lower=True,
                               min_overlap={"t1": 0.25})
    # Raising the floor needs no ceremony.
    baseline.write_overlap(_doc(0.95), path=path)
    assert baseline.load_overlap(path)["targets"]["t1"][
        "overlap_score"] == 0.95
    # A target VANISHING from the audit doc must not silently drop
    # its baselined floor either.
    with pytest.raises(ValueError, match="DROP"):
        baseline.write_overlap(_doc(0.5, target="other"), path=path)
    assert baseline.load_overlap(path)["targets"]["t1"][
        "overlap_score"] == 0.95
    baseline.write_overlap(_doc(0.5, target="other"), path=path,
                           allow_lower=True)
    assert "t1" not in baseline.load_overlap(path)["targets"]


def test_committed_overlap_baseline_matches_targets():
    """The committed OVERLAP_baseline.json covers every audit target
    with a min_overlap pin, at or above the pin — the gate's
    pin/baseline pair must be self-consistent as committed."""
    from distributed_training_tpu.analysis import targets
    doc = baseline.load_overlap()
    for t in targets.TARGETS.values():
        if t.min_overlap is None:
            continue
        row = doc["targets"].get(t.name)
        assert row is not None, f"{t.name} pinned but not baselined"
        assert row["overlap_score"] >= t.min_overlap


# -- ProfileCapture --------------------------------------------------------


def test_parse_profile_at():
    assert parse_profile_at("") == ()
    assert parse_profile_at("20") == (20,)
    assert parse_profile_at("500,20,20") == (20, 500)
    with pytest.raises(ValueError):
        parse_profile_at("20,x")


def test_profile_capture_scheduled_one_shot(tmp_path):
    """A scheduled capture fires once, attributes a real trace, and
    stays fired across a 'restart' (a fresh instance over the same
    run dir — the faults-ledger discipline)."""
    import jax
    import jax.numpy as jnp

    run_dir = str(tmp_path)
    pc = ProfileCapture(run_dir, at_steps="3", n_steps=1)
    assert not pc.maybe_start(1)
    assert pc.maybe_start(3)
    f = jax.jit(lambda x: (x @ x).sum())
    f(jnp.ones((64, 64))).block_until_ready()
    rep = pc.maybe_stop(3, sync=lambda: None)
    assert rep is not None and "error" not in rep
    assert rep["steps_captured"] == 1
    assert rep["compute_frac"] + rep["collective_frac"] \
        + rep["host_frac"] == pytest.approx(1.0, abs=1e-4)
    assert os.path.isdir(os.path.join(run_dir, rep["trace_dir"]))
    # restart: same dir, same schedule → already fired.
    pc2 = ProfileCapture(run_dir, at_steps="3", n_steps=1)
    assert not pc2.maybe_start(3)
    assert not pc2.maybe_start(10)  # at-or-after, still one-shot


def test_profile_capture_one_capture_satisfies_all_stale_triggers(
        tmp_path):
    """A resume landing past several profile_at steps runs ONE
    capture, not one per stale entry back-to-back."""
    import jax
    import jax.numpy as jnp

    pc = ProfileCapture(str(tmp_path), at_steps="20,500", n_steps=1)
    assert pc.maybe_start(600)
    jax.jit(lambda x: x + 1)(jnp.ones((4,))).block_until_ready()
    rep = pc.maybe_stop(600, sync=lambda: None)
    assert rep is not None and rep["trigger"] == "step_20"
    assert not pc.maybe_start(601)  # step_500 satisfied by the same
    # ...and the satisfaction is persisted across a restart.
    pc2 = ProfileCapture(str(tmp_path), at_steps="20,500", n_steps=1)
    assert not pc2.maybe_start(602)


def test_profile_capture_drop_file_trigger(tmp_path):
    import jax
    import jax.numpy as jnp

    run_dir = str(tmp_path)
    pc = ProfileCapture(run_dir, at_steps=(), n_steps=1)
    assert not pc.maybe_start(5)  # nothing scheduled, no file
    trigger = os.path.join(run_dir, "profile_now")
    with open(trigger, "w"):
        pass
    assert pc.maybe_start(6)
    assert not os.path.exists(trigger)  # consumed
    jax.jit(lambda x: x * 2)(jnp.ones((8,))).block_until_ready()
    rep = pc.maybe_stop(6, sync=lambda: None)
    assert rep is not None and rep["trigger"] == "file_at_6"


def test_profile_capture_disabled_never_fires(tmp_path):
    pc = ProfileCapture(str(tmp_path), at_steps="1", enabled=False)
    assert not pc.maybe_start(1)
    assert pc.maybe_stop(1) is None


# -- metrics endpoint ------------------------------------------------------


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5)


def test_metrics_server_exposition_and_healthz(tmp_path):
    tel = telemetry.Telemetry(
        events_jsonl=str(tmp_path / "events.jsonl"))
    srv = MetricsServer(0, telemetry=tel, tokens_per_step=1024,
                        stall_timeout_s=0.4,
                        info={"world_size": 4,
                              "incarnation": 0}).start()
    assert srv is not None and srv.port
    try:
        with tel.span("step", step=1):
            time.sleep(0.01)
        with tel.span("data_wait", step=2):
            pass
        tel.event("goodput", scope="window", step=2, mfu_wall=0.31,
                  goodput=0.8, buckets={})
        tel.event("attribution", step=3, overlap_frac=0.42,
                  compute_frac=0.5, collective_frac=0.2,
                  host_frac=0.3)
        tel.event("attribution_static", step=1, overlap_score=0.32,
                  scored=63)
        tel.event("straggler", step=100, persistent=["host 3 slow"])
        tel.event("resume", step=5, world_size=3, restarts=2)
        body = _get(srv.port, "/metrics").read().decode()
        # The acceptance surface: every advertised metric name.
        for want in ("dtt_mfu 0.31", "dtt_tokens_per_s",
                     "dtt_goodput 0.8", "dtt_data_wait_seconds_total",
                     "dtt_overlap_fraction 0.42",
                     "dtt_overlap_static_fraction 0.32",
                     "dtt_world_size 3", "dtt_incarnation 2",
                     "dtt_straggler_verdicts_total 1",
                     "dtt_step_time_seconds", "dtt_steps_total 1",
                     "dtt_up 1"):
            assert want in body, (want, body)
        # Valid Prometheus text exposition: every sample line's metric
        # has HELP + TYPE, values parse as floats.
        names = set()
        for line in body.strip().splitlines():
            if line.startswith("# "):
                continue
            name, val = line.split(" ", 1)
            float(val)
            names.add(name)
        for n in names:
            assert f"# TYPE {n} " in body
        # healthz: ok while fresh, 503 once stalled past threshold.
        assert _get(srv.port, "/healthz").status == 200
        time.sleep(0.6)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "stalled"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()
        tel.close()


def test_metrics_server_healthz_compile_allowance():
    """Before the first step the stall budget is 10x (the watchdog's
    compile allowance) — a compiling run is 'starting', not dead."""
    srv = MetricsServer(0, stall_timeout_s=5.0)
    healthy, detail = srv.health()
    assert healthy and detail["status"] == "starting"
    # The FIRST optimizer step dispatches under a "compile" span:
    # it must count as a step and flip the latch to the 1x budget.
    srv.observe({"kind": "span", "name": "compile", "dur_s": 2.0})
    healthy, detail = srv.health()
    assert healthy and detail["status"] == "ok"
    assert detail["steps"] == 1


def test_metrics_server_observer_failure_does_not_break_sink(
        tmp_path):
    """A broken observer must not disturb emission (the endpoint is a
    consumer of the stream, never a gate on it)."""
    path = str(tmp_path / "events.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    tel.add_observer(lambda rec: (_ for _ in ()).throw(
        RuntimeError("observer boom")))
    tel.event("goodput", scope="window", step=1)
    tel.close()
    assert [e for e in _read_jsonl(path) if e["kind"] == "goodput"]


# -- trainer end-to-end ----------------------------------------------------


def _demo(rt, tmp_path, **train_over):
    cfg = Config()
    cfg.train.batch_size = 4
    cfg.train.total_epochs = 3
    cfg.train.save_every = 0
    cfg.train.log_every = 1
    cfg.train.dataset_size = 32
    cfg.train.metrics_jsonl = str(tmp_path / "run" / "metrics.jsonl")
    cfg.train.events_jsonl = str(tmp_path / "run" / "events.jsonl")
    for k, v in train_over.items():
        setattr(cfg.train, k, v)
    model = build_model("mlp", input_size=20, output_size=1,
                        loss="mse")
    ds = SyntheticRegressionDataset(size=32, in_dim=20, out_dim=1,
                                    seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=4)
    return cfg, model, loader


def test_trainer_emits_attribution_events(cpu8, tmp_path):
    """The acceptance path: a CPU run with a profile trigger produces
    an `attribution` event whose fractions sum to ~1.0 with an
    overlap %, plus the one-shot `attribution_static` after first
    compile — and the summarizer renders both."""
    cfg, model, loader = _demo(cpu8, tmp_path)
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    run_dir = str(tmp_path / "run")
    pc = ProfileCapture(run_dir, at_steps="2", n_steps=1)
    trainer = Trainer(cfg, cpu8, model, loader, profile_capture=pc)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])
    events = _read_jsonl(cfg.train.events_jsonl)

    att = [e for e in events if e["kind"] == "attribution"]
    assert len(att) == 1, att
    a = att[0]
    assert "error" not in a
    assert a["schema"] == attribution.SCHEMA
    assert a["compute_frac"] + a["collective_frac"] + a["host_frac"] \
        == pytest.approx(1.0, abs=1e-4)
    assert 0.0 <= a["overlap_frac"] <= 1.0
    assert a["events"] > 0

    static = [e for e in events if e["kind"] == "attribution_static"]
    assert len(static) == 1
    assert static[0]["schema"] == attribution.OVERLAP_SCHEMA
    assert "overlap_score" in static[0]

    from distributed_training_tpu.telemetry.summarize import (
        render, summarize_run)
    summary_doc = summarize_run(run_dir)
    assert summary_doc["attribution"]["overlap_frac"] == \
        a["overlap_frac"]
    assert "attribution (step" in render(summary_doc)


def test_trainer_attribution_failure_does_not_kill_run(
        cpu8, tmp_path, monkeypatch):
    """A broken trace parse degrades to an `attribution` event with
    an error field; the run finishes (the collectives-audit
    contract)."""
    cfg, model, loader = _demo(cpu8, tmp_path)
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    monkeypatch.setattr(
        attribution, "attribute_trace_dir",
        lambda d: (_ for _ in ()).throw(
            xplane.XplaneError("parse boom")))
    pc = ProfileCapture(str(tmp_path / "run"), at_steps="2",
                        n_steps=1)
    trainer = Trainer(cfg, cpu8, model, loader, profile_capture=pc)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])
    att = [e for e in _read_jsonl(cfg.train.events_jsonl)
           if e["kind"] == "attribution"]
    assert len(att) == 1 and "parse boom" in att[0]["error"]


# -- multi-host aggregate (additive keys, schema pinned) -------------------


def test_aggregate_carries_attribution_schema_stays_1(tmp_path):
    from distributed_training_tpu.telemetry import aggregate
    run = tmp_path / "run"
    for h in (0, 1):
        d = run / f"host_{h}"
        d.mkdir(parents=True)
        with open(d / "events.jsonl", "w") as f:
            f.write(json.dumps({"kind": "run_start", "t": 0.0,
                                "step": 0, "host": h}) + "\n")
            f.write(json.dumps({"kind": "clock_sync", "t": 0.1,
                                "t_sync": 100.0, "process_index": h,
                                "process_count": 2,
                                "host": h}) + "\n")
            if h == 0:
                f.write(json.dumps(
                    {"kind": "attribution", "t": 1.0, "host": 0,
                     "step": 4, "overlap_frac": 0.4,
                     "compute_frac": 0.5, "collective_frac": 0.1,
                     "host_frac": 0.4, "source": "device"}) + "\n")
                f.write(json.dumps(
                    {"kind": "attribution_static", "t": 1.1,
                     "host": 0, "step": 1, "overlap_score": 0.32,
                     "scored": 63, "overlapped": 20,
                     "mean_compute_between": 3.0}) + "\n")
    summary = aggregate.aggregate_run(str(run))
    assert summary["schema"] == 1  # additive keys only
    assert summary["attribution"]["overlap_frac"] == 0.4
    assert summary["attribution_static"]["overlap_score"] == 0.32
    text = aggregate.render_multihost(summary)
    assert "attribution (step" in text
    assert "static overlap" in text
