"""Ulysses (all-to-all) sequence parallelism: correctness vs full
attention on the 8-device CPU mesh, gradient parity through autodiff
(a2a transposes to the inverse a2a), GQA alignment, and end-to-end
train-step parity vs plain data parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.ops.attention import _naive_attention
from distributed_training_tpu.parallel.ulysses import (
    ulysses_attention_global,
)
from distributed_training_tpu.runtime import fake_cpu_runtime

# This container's pinned jax runs the Pallas kernels in interpret
# mode and the ring/pipeline numerics at minutes per test — far over
# the tier-1 wall-clock budget (the whole file was broken-at-import
# at seed, so the fast gate never paid for it). The fast gate still
# COMPILES these paths every run (the analysis SPMD audit target
# lowers ring attention under the full sharded train step; the
# test_benchmarks contract tests compile the strategy matrix); the
# kernel/numerics suites here run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def rand_qkv(B=2, S=64, H=4, D=16, Hkv=None, seed=0):
    Hkv = Hkv or H
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, S, H, D)),
            jax.random.normal(ks[1], (B, S, Hkv, D)),
            jax.random.normal(ks[2], (B, S, Hkv, D)))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_matches_full(causal, sp):
    rt = fake_cpu_runtime(8, sp=sp)
    q, k, v = rand_qkv()
    out = ulysses_attention_global(q, k, v, rt.mesh, causal=causal)
    ref = _naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_head_alignment():
    """Hkv-grouped heads: the head-split a2a must keep each q-head
    chunk aligned with its kv-head chunk (Hkv % sp == 0 case)."""
    rt = fake_cpu_runtime(8, sp=2)
    q, k, v = rand_qkv(H=8, Hkv=4)
    out = ulysses_attention_global(q, k, v, rt.mesh, causal=True)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(H=8, Hkv=2)  # Hkv=2 not divisible by sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_global(q, k, v, rt.mesh, causal=True)


def test_ulysses_gradients_match_full():
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(S=32, H=4, D=8)

    def loss_u(q, k, v):
        return jnp.sum(
            ulysses_attention_global(q, k, v, rt.mesh,
                                     causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gu, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch")


def test_ulysses_training_end_to_end_matches_dp():
    """Train-step loss trajectory with attention_impl=ulysses on a
    (dp=2, sp=4) mesh == naive attention on a plain dp=2 mesh."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, impl in (("dp", 2, {}, "naive"),
                                  ("sp", 8, {"sp": 4}, "ulysses")):
        rt = fake_cpu_runtime(ndev, **axes)
        assert rt.data_shard_count == 2
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=impl))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["sp"],
                               rtol=1e-5, atol=1e-6)


def test_ulysses_tp_composition_matches_full():
    """Heads sharded over tp AND traded for sequence by the sp a2a:
    the composed layout must still be exact (needs H, Hkv % tp*sp)."""
    rt = fake_cpu_runtime(8, sp=2, tp=2)
    q, k, v = rand_qkv(H=8, Hkv=4)
    out = ulysses_attention_global(q, k, v, rt.mesh, causal=True,
                                   head_axis="tp")
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_tp_training_end_to_end_matches_dp():
    """Train-step losses with attention_impl=ulysses on a
    (dp=2, sp=2, tp=2) mesh == naive attention on a plain dp=2 mesh."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, impl in (
            ("dp", 2, {}, "naive"),
            ("tp_sp", 8, {"sp": 2, "tp": 2}, "ulysses")):
        rt = fake_cpu_runtime(ndev, **axes)
        assert rt.data_shard_count == 2
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=impl))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["tp_sp"],
                               rtol=1e-5, atol=1e-6)


def test_ulysses_tp_rejects_indivisible_heads():
    """tp*sp exceeding the kv-head count must fail loudly, with
    GLOBAL head counts in the message (the in-shard_map check would
    report confusing per-shard numbers)."""
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    rt = fake_cpu_runtime(8, sp=2, tp=2)
    model = Transformer(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
        max_seq_len=16, dtype="float32", attention_impl="ulysses"))
    model.bind_mesh(rt.mesh)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 9), jnp.int32)
    with pytest.raises(ValueError, match="tp\\*sp"):
        jax.jit(lambda p, b: model.loss(p, b, jax.random.PRNGKey(0)))(
            params, {"tokens": tokens})


# Pairwise coverage of (impl, schedule, pos_encoding) in four runs.
# n_layers=4 with pp_virtual_stages=2 makes the interleaved cases
# non-degenerate (2 chunks/device — lax.switch really selects, the
# collective-bearing stage body runs under real interleaving).
@pytest.mark.parametrize("impl,schedule,pos_encoding", [
    ("ulysses", "gpipe", "learned"),
    ("ulysses", "interleaved", "rope"),
    ("ring", "gpipe", "rope"),
    ("ring", "interleaved", "learned"),
])
def test_seqparallel_pp_composition_matches_dp(impl, schedule,
                                               pos_encoding):
    """Pipeline (pp=2) with sequence-parallel attention (sp=2): the
    stage body calls the collective-level attention (ulysses a2a, or
    the ring with its reverse-ring custom VJP under the checkpointed
    tick) inside the pipeline shard_map — no nested shard_map.
    Activations stay sequence-sharded through the pp ppermute and rope
    positions are offset per sp shard; losses must match plain dp."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, attn in (
            ("dp", 2, {}, "naive"),
            ("pp_sp", 8, {"pp": 2, "sp": 2}, impl)):
        rt = fake_cpu_runtime(ndev, **axes)
        assert rt.data_shard_count == 2
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=attn,
            pos_encoding=pos_encoding, pp_microbatches=2,
            pp_schedule=schedule, pp_virtual_stages=2))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["pp_sp"],
                               rtol=1e-5, atol=1e-6)


def test_windowed_ring_under_pipeline_matches_dp():
    """pp=2 x sp=2 with a sliding window that EXCEEDS the local S/sp
    shard but not the global sequence (window=10 > S_local=8): the
    normalization must compare against the GLOBAL length, or this
    silently degrades to full causal — pinned by matching the plain-dp
    windowed loss (which differs measurably from full causal)."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, attn, window in (
            ("dp_full", 2, {}, "naive", 0),
            ("dp_win", 2, {}, "naive", 10),
            ("pp_sp_win", 8, {"pp": 2, "sp": 2}, "ring", 10)):
        rt = fake_cpu_runtime(ndev, **axes)
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=attn,
            attention_window=window, pos_encoding="rope",
            pp_microbatches=2))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    # The window changes the trajectory vs full causal...
    assert any(abs(a - b) > 1e-6 for a, b in
               zip(losses["dp_full"], losses["dp_win"]))
    # ...and the pp x sp windowed ring reproduces the windowed dp one.
    np.testing.assert_allclose(losses["dp_win"], losses["pp_sp_win"],
                               rtol=1e-5, atol=1e-6)


def test_windowed_ring_interleaved_pipeline_matches_dp():
    """Window + ring + INTERLEAVED virtual stages (the deepest
    schedule composition): must reproduce the plain-dp windowed loss
    like the GPipe variant above."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, attn in (
            ("dp_win", 2, {}, "naive"),
            ("pp_sp_win", 8, {"pp": 2, "sp": 2}, "ring")):
        rt = fake_cpu_runtime(ndev, **axes)
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=attn,
            attention_window=10, pos_encoding="rope",
            pp_microbatches=2, pp_schedule="interleaved",
            pp_virtual_stages=2))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp_win"], losses["pp_sp_win"],
                               rtol=1e-5, atol=1e-6)
