"""The local lint gate actually RUNS here (VERDICT r4 item 5).

The CI lint job mirrors the reference's four gates
(black/flake8/isort/mypy, reference .github/workflows/lint.yml:20-25)
but has never executed in this container — no runner, no tools, no
network. tools/lint_local.py implements the mechanically-checkable
subset (E501/W291/W293/W191/E711/E712/F401 + import-group order) plus
the DTT0xx pitfall-rule registry shared with
``distributed_training_tpu/analysis/pitfalls.py``; this test makes
`pytest tests/` red when a violation lands, which is the "gates have
actually run on HEAD" evidence the CI job cannot provide here. The
full static-analysis gate (``python -m distributed_training_tpu
.analysis --check`` — pitfall rules AND the SPMD audit ratchet) runs
here too, so tier-1 is red on any new audit finding. black formatting
and mypy typing remain CI-only (documented in tools/lint_local.py —
no pretend coverage).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_passes_local_lint_subset():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_local.py")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, f"lint violations:\n{out.stdout}"


def test_repo_passes_static_analysis_check():
    """The full gate: DTT rules clean AND the SPMD audit reproduces
    only baselined findings (ratchet), with per-target pin_zero pins
    honored — the planned target (multichip_r06_planned) compiling
    with ANY involuntary-reshard warning makes this red, which is the
    'zero reshards on the chosen plan' acceptance gate."""
    out = subprocess.run(
        [sys.executable, "-m", "distributed_training_tpu.analysis",
         "--check", "--json", "-"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stdout + "\n" + out.stderr


def test_repo_passes_planner_check():
    """The planner gate: every committed plan in conf/plans/ is still
    the deterministic search's winner (ranking, winner identity,
    sharding-map fingerprint) and carries clean compile evidence.
    The recompile that re-proves reshard-cleanliness on this XLA is
    owned by the analysis gate above (multichip_r06_planned target),
    so this stays cheap."""
    out = subprocess.run(
        [sys.executable, "-m",
         "distributed_training_tpu.parallel.planner", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stdout + "\n" + out.stderr


def test_repo_passes_perf_ledger_check():
    """The perf-ledger gate: every committed *_r*.json ledger chain
    (compared_to copies, speedup gates, revision contiguity) still
    reproduces. Stdlib-only and invoked BY PATH like lint_local —
    no package import, no jax. tests/test_perf_ledger.py owns the
    red cases on tampered copies; this is the tier-1 wiring."""
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "perf_ledger.py"), "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stdout + "\n" + out.stderr


def test_lint_and_analysis_share_one_rule_table():
    """lint_local must run the registry, not a private copy — the
    two gates drifting is the failure mode the refactor removes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    assert {"DTT001", "DTT002", "DTT003", "DTT004", "DTT005",
            "DTT006", "DTT007", "DTT008", "DTT009", "DTT010",
            "DTT011"} <= set(lint_local.pitfalls.RULES)


def test_lint_local_catches_violations(tmp_path):
    """The gate is live, not vacuous: a file with known violations in
    every implemented class is flagged."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "import json, sys\n"
        "x = 1 " + "\n"               # trailing whitespace
        "if x == " + "None:\n"
        "\tpass\n"                    # tab
        "y = '" + "z" * 120 + "'\n"
        "f = open('events.jsonl', 'w')\n")  # bypasses the event sink
    problems = lint_local.check_file(str(bad))
    codes = {p.split()[1] for p in problems}
    assert {"E501", "W291", "W191", "E711", "F401",
            "DTT001"} <= codes, problems


def test_lint_local_jsonl_rule_scoping(tmp_path):
    """DTT001 scoping: read-mode opens and noqa'd derived-artifact
    writes pass; the sink modules themselves are exempt by path."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    ok = tmp_path / "ok.py"
    ok.write_text(
        "rows = open('events.jsonl').read()\n"
        "art = open('tail.jsonl', 'w')  # noqa: DTT001\n"
        "bare = open('tail2.jsonl', 'w')  # noqa\n")
    assert not [p for p in lint_local.check_file(str(ok))
                if "DTT001" in p]
    # A noqa for a DIFFERENT code must not disable this rule.
    other = tmp_path / "other.py"
    other.write_text("x = open('events.jsonl', 'w')  # noqa: E501\n")
    assert [p for p in lint_local.check_file(str(other))
            if "DTT001" in p]
    # The sink itself writes jsonl by definition.
    sink = os.path.join(REPO, "distributed_training_tpu",
                        "telemetry", "events.py")
    assert not [p for p in lint_local.check_file(sink)
                if "DTT001" in p]


def test_lint_local_silent_swallow_rule(tmp_path):
    """DTT002: broad `except ...: pass` fails; narrow handlers,
    handlers that do something, and justified noqa'd swallows pass."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
        "try:\n    x = 2\nexcept:\n    pass\n"
        "try:\n    x = 3\nexcept (ValueError, BaseException):\n"
        "    pass\n")
    hits = [p for p in lint_local.check_file(str(bad))
            if "DTT002" in p]
    assert len(hits) == 3, hits
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import logging\n"
        "try:\n    x = 1\nexcept FileNotFoundError:\n    pass\n"
        "try:\n    x = 2\nexcept Exception as e:\n"
        "    logging.debug('%s', e)\n"
        "try:\n    x = 3\nexcept Exception:  # noqa: DTT002\n"
        "    pass\n")
    assert not [p for p in lint_local.check_file(str(ok))
                if "DTT002" in p]
    # A noqa for a DIFFERENT code must not disable this rule.
    other = tmp_path / "other.py"
    other.write_text(
        "try:\n    x = 1\nexcept Exception:  # noqa: E501\n"
        "    pass\n")
    assert [p for p in lint_local.check_file(str(other))
            if "DTT002" in p]


def test_lint_local_serving_sync_rule():
    """DTT010: host syncs in serving/ outside the designated helpers
    fail; the helpers themselves, `jnp.asarray`, `np.array`, and
    noqa'd deliberate syncs pass; files outside serving/ are out of
    scope (DTT003 owns the trainer). Uses `text=` against serving
    rel paths so nothing is written into the package."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    pf = lint_local.pitfalls
    eng = os.path.join(REPO, "distributed_training_tpu", "serving",
                       "engine.py")
    bad = (
        "import jax\nimport numpy as np\n"
        "def step(x):\n"
        "    a = jax.device_get(x)\n"
        "    x.block_until_ready()\n"
        "    b = np.asarray(x)\n"
        "    return a, b\n")
    hits = [p for p in pf.check_file_rules(eng, repo=REPO, text=bad)
            if "DTT010" in p]
    assert len(hits) == 3, hits
    ok = (
        "import jax\nimport jax.numpy as jnp\nimport numpy as np\n"
        "def _fetch_host(*arrays):\n"
        "    return jax.device_get(arrays)\n"
        "def step(x, raw):\n"
        "    y = jnp.asarray(raw)\n"
        "    z = np.array(raw, np.int32)\n"
        "    w = jax.device_get(x)  # noqa: DTT010\n"
        "    return y, z, w\n")
    assert not [p for p in pf.check_file_rules(eng, repo=REPO, text=ok)
                if "DTT010" in p]
    # A noqa for a DIFFERENT code must not disable this rule.
    other = ("import jax\n"
             "def step(x):\n"
             "    return jax.device_get(x)  # noqa: E501\n")
    assert [p for p in pf.check_file_rules(eng, repo=REPO, text=other)
            if "DTT010" in p]
    # Outside serving/ the rule does not apply (DTT003 owns the
    # trainer's hot path; this one owns serving's).
    tr = os.path.join(REPO, "distributed_training_tpu", "train",
                      "somewhere.py")
    assert not [p for p in pf.check_file_rules(tr, repo=REPO, text=bad)
                if "DTT010" in p]
    # disagg's KV export/import are the other designated sync point:
    # their np.asarray on device slices IS the prefill→decode handoff.
    dis = os.path.join(REPO, "distributed_training_tpu", "serving",
                       "disagg.py")
    helper = ("import numpy as np\n"
              "def export_kv_batch(cache, seq_ids):\n"
              "    return np.asarray(cache)\n"
              "def elsewhere(cache):\n"
              "    return np.asarray(cache)\n")
    hits = [p for p in pf.check_file_rules(dis, repo=REPO, text=helper)
            if "DTT010" in p]
    assert len(hits) == 1 and ":5:" in hits[0], hits


def test_lint_local_params_rebinding_rule():
    """DTT011: `.params` rebinding in serving/ outside the sanctioned
    sites (Engine.__init__/swap_weights, WeightStore.__init__) fails;
    the sanctioned sites, reads, local variables named params, and
    noqa'd rebinding pass; files outside serving/ are out of scope."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_local
    finally:
        sys.path.pop(0)
    pf = lint_local.pitfalls
    eng = os.path.join(REPO, "distributed_training_tpu", "serving",
                       "engine.py")
    bad = (
        "def hot_patch(self, new):\n"
        "    self.params = new\n"
        "def nudge(self, g):\n"
        "    self.params += g\n")
    hits = [p for p in pf.check_file_rules(eng, repo=REPO, text=bad)
            if "DTT011" in p]
    assert len(hits) == 2, hits
    ok = (
        "def __init__(self, params):\n"
        "    self.params = params\n"
        "def swap_weights(self, params, version):\n"
        "    self.params = params\n"
        "def read_only(self):\n"
        "    params = self.params\n"
        "    return params\n"
        "def justified(self, new):\n"
        "    self.params = new  # noqa: DTT011\n")
    assert not [p for p in pf.check_file_rules(eng, repo=REPO, text=ok)
                if "DTT011" in p]
    # A noqa for a DIFFERENT code must not disable this rule.
    other = ("def hot_patch(self, new):\n"
             "    self.params = new  # noqa: E501\n")
    assert [p for p in pf.check_file_rules(eng, repo=REPO, text=other)
            if "DTT011" in p]
    # WeightStore.__init__ loads the artifact's params legitimately;
    # any other disagg function rebinding is flagged.
    dis = os.path.join(REPO, "distributed_training_tpu", "serving",
                       "disagg.py")
    store = ("def __init__(self, path):\n"
             "    self.params = {}\n"
             "def reload(self, path):\n"
             "    self.params = {}\n")
    hits = [p for p in pf.check_file_rules(dis, repo=REPO, text=store)
            if "DTT011" in p]
    assert len(hits) == 1 and ":4:" in hits[0], hits
    # Outside serving/ the rule does not apply (the trainer rebinds
    # params every step by design).
    tr = os.path.join(REPO, "distributed_training_tpu", "train",
                      "somewhere.py")
    assert not [p for p in pf.check_file_rules(tr, repo=REPO, text=bad)
                if "DTT011" in p]
    # The rule is live against the REAL tree: zero offenders today.
    for rel, fns in pf.DTT011_ALLOWED.items():
        assert fns, rel
    real = [p for p in pf.check_file_rules(eng, repo=REPO)
            if "DTT011" in p]
    assert real == [], real
