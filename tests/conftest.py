"""Test fixture: simulate an 8-device TPU slice on CPU.

The CPU analogue of the reference's Gloo/CPU cluster simulation
(reference: src/distributed_trainer.py:55-61, src/playground/ddp_script.py:
230-234): all sharding/collective tests run on 8 fake CPU devices so the
full multi-chip path is exercised without TPU hardware. Must run before
jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Keep test compiles fast & deterministic.
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Unit tests exercise bench.main() (in-process and as a subprocess) —
# its claim-the-chip pkill sweep must never fire against live host
# processes from a test run.
os.environ["DTT_BENCH_NO_CLAIM"] = "1"
# The device-less TPU-topology tests initialize libtpu, which on a
# non-GCP host (or one whose metadata server answers 403) retries the
# instance-metadata fetch 30x per variable — minutes of wall-clock at
# 0% CPU before the init even fails. Skip the metadata query outright:
# topology descriptors don't need it, and the suite must not wedge on
# a dead metadata endpoint.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")

import jax  # noqa: E402

# Site customizations may pin jax_platforms to the hardware plugin at
# interpreter startup, overriding the env var — force CPU back on.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu8():
    """Session-wide 8-device CPU runtime with a pure-DP mesh."""
    from distributed_training_tpu.runtime import fake_cpu_runtime
    return fake_cpu_runtime(8)


@pytest.fixture(scope="session", autouse=True)
def _check_devices():
    assert jax.device_count() >= 8, (
        "conftest failed to fake 8 cpu devices; got "
        f"{jax.device_count()}")
