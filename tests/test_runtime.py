"""Runtime/mesh layer tests on 8 fake CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_tpu.config import Config, MeshConfig
from distributed_training_tpu.runtime import (
    MeshSpec, RuntimeError_, build_mesh, fake_cpu_runtime,
    initialize_runtime, runtime_for_mesh,
)


def test_mesh_spec_resolve_fill():
    spec = MeshSpec.resolve(MeshConfig(dp=-1, fsdp=2), 8)
    assert spec.dp == 4 and spec.fsdp == 2 and spec.total == 8


def test_mesh_spec_resolve_exact():
    spec = MeshSpec.resolve(MeshConfig(dp=2, fsdp=2, tp=2), 8)
    assert spec.total == 8


def test_mesh_spec_mismatch_raises():
    with pytest.raises(RuntimeError_):
        MeshSpec.resolve(MeshConfig(dp=3, fsdp=1), 8)
    with pytest.raises(RuntimeError_):
        MeshSpec.resolve(MeshConfig(dp=-1, fsdp=3), 8)
    with pytest.raises(RuntimeError_):
        MeshSpec.resolve(MeshConfig(dp=-1, fsdp=-1), 8)


def test_build_mesh_axes():
    spec = MeshSpec(dp=2, fsdp=2, sp=2, tp=1, pp=1)
    mesh = build_mesh(spec, jax.devices("cpu")[:8])
    assert mesh.axis_names == ("pp", "dp", "fsdp", "sp", "tp")
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["dp"] == 2


def test_initialize_runtime_cpu():
    cfg = Config()
    cfg.train.device = "cpu"
    rt = initialize_runtime(cfg)
    assert rt.num_devices == 8
    assert rt.spec.dp == 8  # -1 filled
    assert rt.is_coordinator
    assert rt.data_shard_count == 8
    assert "mesh" in rt.describe()


def test_fake_cpu_runtime_axes():
    rt = fake_cpu_runtime(8, fsdp=4)
    assert rt.spec.fsdp == 4 and rt.spec.dp == 2


def test_batch_sharding_places_shards(cpu8):
    x = jnp.arange(16.0).reshape(16, 1)
    y = jax.device_put(x, cpu8.batch_sharding)
    assert len(y.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_psum_over_mesh(cpu8):
    """XLA collective smoke test: jit + sharding constraint produces the
    same result as unsharded compute (the compiled-allreduce path that
    replaces NCCL; SURVEY.md §2.2)."""
    x = jnp.ones((8, 4))

    @jax.jit
    def f(x):
        x = jax.lax.with_sharding_constraint(x, cpu8.batch_sharding)
        return x.sum()

    assert float(f(x)) == 32.0


def test_runtime_for_mesh_roundtrip(cpu8):
    rt = runtime_for_mesh(cpu8.mesh)
    assert rt.spec == cpu8.spec


def test_sharding_helper(cpu8):
    s = cpu8.sharding("dp", None)
    assert s.spec == P("dp", None)


def test_mesh_zero_and_negative_sizes_rejected():
    with pytest.raises(RuntimeError_):
        MeshSpec.resolve(MeshConfig(dp=-1, fsdp=0), 8)
    with pytest.raises(RuntimeError_):
        MeshSpec.resolve(MeshConfig(dp=-2, fsdp=1), 8)
