"""Integration: local multi-process launch (the torchrun analogue).

The framework's counterpart of the reference playground's
``mp.spawn``-based CPU cluster simulation (src/playground/ddp_script.py:
244-256): two OS processes, each simulating a 2-device host, rendezvous
via ``jax.distributed`` at a local TCP coordinator and run the real CLI
end-to-end (config → runtime → data → trainer → checkpoint).
"""

import os

import pytest

from distributed_training_tpu.launch import local as launch_local_mod


@pytest.mark.slow
def test_two_process_training_run(tmp_path):
    log_dir = str(tmp_path / "logs")
    out_dir = str(tmp_path / "run")
    snap = str(tmp_path / "ckpt")
    procs = launch_local_mod.launch_local(
        [
            "-m", "distributed_training_tpu.train",
            f"run.output_dir={out_dir}",
            f"train.snapshot_path={snap}",
            "train.total_epochs=2",
            "train.dataset_size=64",
            "train.batch_size=8",
            "train.log_every=0",
            # exercise the COLLECTIVE consolidated export across
            # processes (every process must enter the gather; B6).
            "train.gather_on_save=true",
        ],
        num_processes=2,
        devices_per_process=2,
        log_dir=log_dir,
        # Children must not inherit the test process's platform pinning
        # in a way that conflicts; the launcher sets cpu + 2 fake devices.
        env={"JAX_PLATFORMS": "cpu"},
    )
    code = launch_local_mod.wait(procs, timeout=420)
    logs = "\n".join(
        open(p.log_path).read() for p in procs if p.log_path)
    assert code == 0, f"multi-process run failed:\n{logs[-4000:]}"
    # Both processes formed one 4-device cluster.
    assert "devices=4" in logs
    assert "processes=2" in logs
    # A checkpoint was written collectively.
    assert os.path.isdir(snap) and os.listdir(snap), (
        "no checkpoint written by multi-process run")
    consolidated = [f for f in os.listdir(snap)
                    if f.startswith("consolidated_")]
    assert consolidated, "collective export produced no artifact"
    from distributed_training_tpu.checkpoint import load_consolidated
    state, meta = load_consolidated(
        os.path.join(snap, sorted(consolidated)[-1]))
    assert "params" in state and "step" in meta


def test_wait_fail_fast(tmp_path):
    """A failing process kills the group (torchrun fail-fast)."""
    procs = launch_local_mod.launch_local(
        ["-c", "import sys,time,os; "
               "sys.exit(3) if os.environ['DTT_PROCESS_ID']=='0' "
               "else time.sleep(600)"],
        num_processes=2,
        log_dir=str(tmp_path),
    )
    code = launch_local_mod.wait(procs, timeout=60)
    assert code == 3
