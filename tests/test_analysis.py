"""The static-analysis subsystem: SPMD auditor + pitfall rules.

Three layers, three speeds:
- pitfall rules (DTT003–DTT006): pure-AST fixtures, instant;
- ratchet arithmetic (baseline.py): synthetic findings, instant;
- the auditor itself: REAL compiles of the two named targets on the
  conftest-faked 8-device CPU mesh — the tp+sp+fsdp dryrun config
  must reproduce the involuntary-reshard finding MULTICHIP_r05.json
  recorded from the log tail, and the single-chip headline config
  must audit clean. Module-scoped fixtures so each target compiles
  once per run.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_training_tpu.analysis import (audit, baseline,
                                               pitfalls, targets)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(tmp_path, src, name="x.py"):
    p = tmp_path / name
    p.write_text(src)
    return pitfalls.check_file_rules(str(p), repo=str(tmp_path))


# ---------------------------------------------------------------------------
# Registry plumbing
# ---------------------------------------------------------------------------


def test_registry_has_all_rules():
    assert {"DTT001", "DTT002", "DTT003", "DTT004", "DTT005",
            "DTT006", "DTT007", "DTT008", "DTT009"} <= set(
                pitfalls.RULES)


def test_tests_directory_is_exempt(tmp_path):
    (tmp_path / "tests").mkdir()
    p = tmp_path / "tests" / "fixture.py"
    p.write_text("f = open('events.jsonl', 'w')\n")
    assert pitfalls.check_file_rules(str(p), repo=str(tmp_path)) == []


# ---------------------------------------------------------------------------
# DTT003 — host sync in the hot step path
# ---------------------------------------------------------------------------

_HOT = {"hot.py": {"train_step"}}


def test_dtt003_flags_host_syncs(tmp_path, monkeypatch):
    monkeypatch.setattr(pitfalls, "DTT003_HOT_PATHS", _HOT)
    problems = _rules(tmp_path, (
        "def train_step(self, batch):\n"
        "    loss = metrics['loss'].item()\n"
        "    x = float(metrics['loss'])\n"
        "    y = jax.device_get(metrics)\n"
        "    arr.block_until_ready()\n"), name="hot.py")
    assert len([p for p in problems if "DTT003" in p]) == 4, problems


def test_dtt003_scoping(tmp_path, monkeypatch):
    monkeypatch.setattr(pitfalls, "DTT003_HOT_PATHS", _HOT)
    # Not a hot function / not a hot file / constant cast / noqa.
    assert not _rules(tmp_path, (
        "def helper(x):\n    return float(x)\n"), name="hot.py")
    assert not _rules(tmp_path, (
        "def train_step(x):\n    return float(x)\n"), name="cold.py")
    assert not _rules(tmp_path, (
        "def train_step(x):\n    return float('nan')\n"),
        name="hot.py")
    assert not _rules(tmp_path, (
        "def train_step(x):\n"
        "    return float(x)  # noqa: DTT003 — epoch drain\n"),
        name="hot.py")


# ---------------------------------------------------------------------------
# DTT004 — collective under a host-local condition
# ---------------------------------------------------------------------------


def test_dtt004_flags_host_local_guards(tmp_path):
    problems = _rules(tmp_path, (
        "def f(self, x):\n"
        "    if self.rt.is_coordinator:\n"
        "        multihost_utils.process_allgather(x)\n"
        "def g(self, x, t0):\n"
        "    while time.perf_counter() - t0 < 5:\n"
        "        jax.lax.psum(x, 'dp')\n"))
    hits = [p for p in problems if "DTT004" in p]
    assert len(hits) == 2, problems
    assert "is_coordinator" in hits[0]
    assert "perf_counter" in hits[1]


def test_dtt004_step_cadence_passes(tmp_path):
    # The straggler/faults discipline: cadence from global_step only.
    assert not _rules(tmp_path, (
        "def f(self, x, global_step):\n"
        "    if global_step % self.every == 0:\n"
        "        multihost_utils.process_allgather(x)\n"
        "    if jax.process_count() > 1:\n"
        "        multihost_utils.sync_global_devices('tag')\n"))


# ---------------------------------------------------------------------------
# DTT005 — PRNG key reuse
# ---------------------------------------------------------------------------


def test_dtt005_flags_key_reuse(tmp_path):
    problems = _rules(tmp_path, (
        "def f():\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"))
    assert len([p for p in problems if "DTT005" in p]) == 1, problems


def test_dtt005_flags_parameter_key_reuse(tmp_path):
    """Keys threaded in as function parameters are the common real
    reuse pattern — the rule tracks them, not just maker-bound
    names; non-key args (shapes, counts) in later positions never
    count as consumptions."""
    problems = _rules(tmp_path, (
        "def apply(params, key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.bernoulli(key, 0.5)\n"))
    assert len([p for p in problems if "DTT005" in p]) == 1, problems
    assert not _rules(tmp_path, (
        "def apply(params, key, key2, n):\n"
        "    a = jax.random.normal(key, n)\n"
        "    b = jax.random.uniform(key2, n)\n"))


def test_dtt005_split_and_rebind_pass(tmp_path):
    assert not _rules(tmp_path, (
        "def f():\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    a = jax.random.normal(k1, (2,))\n"
        "    b = jax.random.uniform(k2, (2,))\n"))
    # fold_in between consumptions is a rebind, not a reuse.
    assert not _rules(tmp_path, (
        "def f():\n"
        "    key = jax.random.PRNGKey(0)\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    key = jax.random.fold_in(key, 1)\n"
        "    b = jax.random.normal(key, (2,))\n"))


# ---------------------------------------------------------------------------
# DTT006 — undonated jitted train step
# ---------------------------------------------------------------------------


def test_dtt006_flags_undonated_step(tmp_path):
    problems = _rules(tmp_path, (
        "step = jax.jit(train_step)\n"
        "self._step_fn = jax.jit(make_train_step(model))\n"))
    assert len([p for p in problems if "DTT006" in p]) == 2, problems


def test_dtt006_donated_or_unrelated_pass(tmp_path):
    assert not _rules(tmp_path, (
        "step = jax.jit(train_step, donate_argnums=(0,))\n"
        "fn = jax.jit(make_train_step(m), donate_argnames=('state',))\n"
        "eval_fn = jax.jit(evaluate)\n"
        "helper = jax.jit(lambda x: x)\n"))


def test_dtt006_decorator_forms(tmp_path):
    """@jax.jit and @partial(jax.jit, ...) are the common ways a step
    gets jitted — the rule must see them, not just the call form."""
    problems = _rules(tmp_path, (
        "@jax.jit\n"
        "def train_step(state, batch):\n"
        "    return state\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def update_step(state, batch, n):\n"
        "    return state\n"))
    assert len([p for p in problems if "DTT006" in p]) == 2, problems
    assert not _rules(tmp_path, (
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def train_step(state, batch):\n"
        "    return state\n"
        "@jax.jit\n"
        "def render_frame(x):\n"
        "    return x\n"))


# ---------------------------------------------------------------------------
# DTT007 — hard-coded world size in elastic hot paths
# ---------------------------------------------------------------------------


def _rules_scoped(tmp_path, src, rel="distributed_training_tpu/train"):
    d = tmp_path / rel
    d.mkdir(parents=True, exist_ok=True)
    p = d / "x.py"
    p.write_text(src)
    return pitfalls.check_file_rules(str(p), repo=str(tmp_path))


def test_dtt007_flags_world_size_literals(tmp_path):
    problems = _rules_scoped(tmp_path, (
        "def f(rt, host_dirs):\n"
        "    if rt.process_count == 2:\n"
        "        pass\n"
        "    if jax.process_count() >= 4:\n"
        "        pass\n"
        "    for h in range(4):\n"
        "        print(host_dirs[h])\n"))
    assert len([p for p in problems if "DTT007" in p]) == 3, problems


def test_dtt007_world_agnostic_forms_pass(tmp_path):
    """0/1 comparisons (single-process check, coordinator gating),
    runtime-derived counts, host-free range loops, noqa, and files
    outside the elastic hot paths are all legal."""
    assert not _rules_scoped(tmp_path, (
        "def f(rt, host_dirs):\n"
        "    single = rt.process_count == 1\n"
        "    coord = rt.process_index == 0\n"
        "    for h in range(rt.process_count):\n"
        "        print(host_dirs[h])\n"
        "    for i in range(4):\n"
        "        print(i)\n"))
    assert not _rules_scoped(tmp_path, (
        "def f(rt):\n"
        "    return rt.process_count == 2  # noqa: DTT007 — fixture\n"))
    # A literal-bounded RETRY loop is not a world-size pin: substring
    # hits like subprocess/multiprocessing/hostname must not trip the
    # host/shard-indexed-state heuristic.
    assert not _rules_scoped(tmp_path, (
        "def f(cmd):\n"
        "    for attempt in range(3):\n"
        "        subprocess.run(cmd)\n"
        "    for i in range(2):\n"
        "        multiprocessing.get_context()\n"
        "        socket.gethostname()\n"))
    # benchmarks/ may pin a world deliberately: out of scope.
    assert not _rules_scoped(tmp_path, (
        "def f(rt, host_dirs):\n"
        "    if rt.process_count == 2:\n"
        "        pass\n"), rel="benchmarks")


# ---------------------------------------------------------------------------
# DTT008 — raw PartitionSpec literals outside the sharding map
# ---------------------------------------------------------------------------


def test_dtt008_flags_axis_literals_in_scope(tmp_path):
    problems = _rules_scoped(tmp_path, (
        "from jax.sharding import PartitionSpec as P\n"
        "a = P('fsdp')\n"
        "b = PartitionSpec(('dp', 'fsdp'), None)\n"
        "c = P(None, 'tp')\n"), rel="distributed_training_tpu/models")
    assert len([p for p in problems if "DTT008" in p]) == 3, problems


def test_dtt008_derived_specs_and_scope_pass(tmp_path):
    # Derived/empty specs in scope are the legitimate model idiom —
    # including strings nested in DERIVED expressions (comparison
    # operands, call args), which are data, not axis names.
    assert not [p for p in _rules_scoped(tmp_path, (
        "from jax.sharding import PartitionSpec as P\n"
        "a = P()\n"
        "b = P(None, None)\n"
        "c = P(b_axes or None, head_ax, None, None)\n"
        "d = P(*sh.spec[1:])\n"
        "e = P(None if kind == 'bias' else head_ax)\n"
        "f = P(sh.axis_for('embed'))\n"),
        rel="distributed_training_tpu/models") if "DTT008" in p]
    # ...but a literal inside a TUPLE argument is an axis name.
    assert [p for p in _rules_scoped(tmp_path, (
        "from jax.sharding import PartitionSpec as P\n"
        "a = P(('dp', 'fsdp'))\n"),
        rel="distributed_training_tpu/models") if "DTT008" in p]
    # Axis literals OUTSIDE models/train (the spec-producer homes)
    # are exactly where they belong.
    assert not [p for p in _rules_scoped(tmp_path, (
        "from jax.sharding import PartitionSpec as P\n"
        "a = P('fsdp', 'tp')\n"),
        rel="distributed_training_tpu/parallel") if "DTT008" in p]
    # noqa escape hatch.
    assert not [p for p in _rules_scoped(tmp_path, (
        "from jax.sharding import PartitionSpec as P\n"
        "a = P('fsdp')  # noqa: DTT008 — deliberate pin\n"),
        rel="distributed_training_tpu/train") if "DTT008" in p]


# ---------------------------------------------------------------------------
# DTT009 — unseeded RNG inside the data pipeline
# ---------------------------------------------------------------------------


def test_dtt009_flags_unseeded_rng_in_data(tmp_path):
    problems = _rules_scoped(tmp_path, (
        "import random\n"
        "import numpy as np\n"
        "def f(rows):\n"
        "    rng = np.random.default_rng()\n"
        "    rng2 = np.random.default_rng(seed=None)\n"
        "    x = np.random.rand(4)\n"
        "    y = np.random.permutation(10)\n"
        "    random.shuffle(rows)\n"
        "    z = random.random()\n"), rel="distributed_training_tpu/data")
    assert len([p for p in problems if "DTT009" in p]) == 6, problems
    assert any("default_rng() without an explicit seed" in p
               for p in problems)
    # Aliased import forms must not dodge the rule.
    problems = _rules_scoped(tmp_path, (
        "from numpy.random import default_rng as mk\n"
        "import numpy.random as npr\n"
        "a = mk()\n"
        "b = npr.rand(4)\n"
        "c = mk([1, 2])\n"), rel="distributed_training_tpu/data")
    assert len([p for p in problems if "DTT009" in p]) == 2, problems


def test_dtt009_seeded_and_scoped_forms_pass(tmp_path):
    # Explicitly seeded constructors ARE the serializable-position
    # discipline; generator methods and jax.random are out of scope.
    assert not [p for p in _rules_scoped(tmp_path, (
        "import numpy as np\n"
        "import jax\n"
        "def f(seed, epoch):\n"
        "    rng = np.random.default_rng([seed, 0, epoch])\n"
        "    g = np.random.Generator(np.random.Philox(key=seed))\n"
        "    x = rng.permutation(10)\n"
        "    k = jax.random.PRNGKey(0)\n"
        "    y = jax.random.normal(k, (2,))\n"),
        rel="distributed_training_tpu/data") if "DTT009" in p]
    # Outside data/ the rule does not apply (models draw jax keys —
    # DTT005's domain).
    assert not [p for p in _rules_scoped(tmp_path, (
        "import numpy as np\n"
        "x = np.random.rand(4)\n"),
        rel="distributed_training_tpu/models") if "DTT009" in p]
    # noqa escape hatch.
    assert not [p for p in _rules_scoped(tmp_path, (
        "import numpy as np\n"
        "x = np.random.rand(4)  # noqa: DTT009 — fixture\n"),
        rel="distributed_training_tpu/data") if "DTT009" in p]


def test_dtt009_zero_offenders_in_repo():
    """The shipped data pipeline must satisfy its own rule: every RNG
    under data/ is constructed from explicit integers."""
    hits = []
    root = os.path.join(REPO, "distributed_training_tpu", "data")
    for path in pitfalls.iter_py_files(root):
        hits += [p for p in pitfalls.check_file_rules(path, repo=REPO)
                 if "DTT009" in p]
    assert hits == [], hits


# ---------------------------------------------------------------------------
# Ratchet (baseline.py)
# ---------------------------------------------------------------------------


def _f(fp):
    return {"code": fp.split(":")[0], "target": "t",
            "fingerprint": fp, "message": fp, "detail": {}}


def test_ratchet_baseline_suppresses_old_fails_new(tmp_path):
    findings = [_f("SPMD001:t:a"), _f("SPMD002:t:b")]
    path = str(tmp_path / "base.json")
    baseline.write(findings, path=path)
    # Same findings: nothing new, nothing stale.
    cmp = baseline.compare(findings, baseline.load(path))
    assert not cmp["new"] and not cmp["stale"]
    assert len(cmp["known"]) == 2
    # A new finding fails; a fixed one goes stale (not a failure).
    cmp = baseline.compare(
        [findings[0], _f("SPMD001:t:c")], baseline.load(path))
    assert [f["fingerprint"] for f in cmp["new"]] == ["SPMD001:t:c"]
    assert cmp["stale"] == ["SPMD002:t:b"]


def test_ratchet_subset_run_scopes_stale_to_selected_targets(tmp_path):
    """A subset audit must not call other targets' baseline entries
    stale — 'not re-checked' is not 'fixed'."""
    path = str(tmp_path / "base.json")
    baseline.write([_f("SPMD001:alpha:x"), _f("SPMD001:beta:y")],
                   path=path)
    cmp = baseline.compare([_f("SPMD001:alpha:x")],
                           baseline.load(path), targets=["alpha"])
    assert not cmp["new"] and not cmp["stale"]
    # ...but a genuinely vanished finding of a SELECTED target is.
    cmp = baseline.compare([], baseline.load(path), targets=["alpha"])
    assert cmp["stale"] == ["SPMD001:alpha:x"]


def test_ratchet_missing_baseline_is_empty(tmp_path):
    cmp = baseline.compare([_f("SPMD001:t:a")],
                           baseline.load(str(tmp_path / "nope.json")))
    assert len(cmp["new"]) == 1


def test_ratchet_schema_mismatch_raises(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"schema": 99, "fingerprints": []}))
    with pytest.raises(ValueError, match="schema"):
        baseline.load(str(p))


# ---------------------------------------------------------------------------
# Reshard-warning parsing (both XLA wordings)
# ---------------------------------------------------------------------------

_OLD_STYLE = (
    "2026-08-03 21:44:58.072291: E external/xla/xla/service/spmd/"
    "spmd_partitioner.cc:613] [spmd] Involuntary full "
    "rematerialization. The compiler was not able to go from sharding "
    "{devices=[1,1,2,4]<=[8] last_tile_dim_replicate} to "
    "{devices=[2,2,1,2]<=[8] last_tile_dim_replicate} without doing a "
    "full rematerialization of the tensor for HLO operation: %gather "
    "= f32[4,32,32]{2,1,0} gather(f32[256,32]{1,0} %all-gather, "
    "s32[4,32,1]{2,1,0} %all-gather), offset_dims={2}, "
    "sharding={devices=[1,1,2,4]<=[8] last_tile_dim_replicate}.\n")
_NEW_STYLE = (
    "W0802 18:12:53.222904 7842 spmd_partitioner.cc:652] [SPMD] "
    "Involuntary full rematerialization. The compiler cannot go from "
    "sharding {devices=[1,1,2,4]<=[8] last_tile_dim_replicate} to "
    "{devices=[2,2,1,2]<=[8] last_tile_dim_replicate} efficiently for "
    "HLO operation %all-gather = f32[4,32,32]{2,1,0} "
    "all-gather(%all-reduce), channel_id=91.\n")


def test_parse_reshard_warnings_both_vintages():
    from distributed_training_tpu.telemetry.collectives import (
        parse_reshard_warnings)
    rows = parse_reshard_warnings(_OLD_STYLE + _NEW_STYLE + "noise\n")
    assert len(rows) == 2
    assert rows[0]["op"] == "gather"
    assert rows[1]["op"] == "all-gather"
    for r in rows:
        assert r["dtype"] == "f32" and r["shape"] == "4,32,32"
        assert "devices=[1,1,2,4]" in r["from_sharding"]
        assert "devices=[2,2,1,2]" in r["to_sharding"]


def test_capture_stderr_fd_sees_fd_writes():
    from distributed_training_tpu.telemetry.collectives import (
        capture_stderr_fd)
    with capture_stderr_fd() as cap:
        os.write(2, b"fd-level write\n")
    assert "fd-level write" in cap.text


# ---------------------------------------------------------------------------
# The auditor: real compiles of the named targets
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tp_sp_fsdp_report():
    return audit.audit_target(
        targets.TARGETS["multichip_r05_tp_sp_fsdp"])


@pytest.fixture(scope="module")
def headline_report():
    return audit.audit_target(
        targets.TARGETS["single_chip_headline"])


def test_auditor_multichip_r05_resharding_fixed(tp_sp_fsdp_report):
    """The MULTICHIP_r05 involuntary-remat repro (the token-embedding
    gather under tp+sp+fsdp, recorded as two %gather/%all-gather
    warnings on f32[4,32,32]) is FIXED by the embedding-table
    gather-for-compute constraint: the same compile now reports zero
    reshard warnings and zero SPMD001 findings — and the target pins
    SPMD001 so the cliff cannot silently return (even baselined).
    The ring's collective-permutes remain, as baselined SPMD002."""
    r = tp_sp_fsdp_report
    assert r["spmd_reshard_warnings"] == 0
    assert not [f for f in r["findings"] if f["code"] == "SPMD001"]
    assert [f for f in r["findings"] if f["code"] == "SPMD002"]
    # The collectives event carries the count mechanically.
    assert r["collectives"]["spmd_reshard_warnings"] == \
        r["spmd_reshard_warnings"]
    assert targets.TARGETS["multichip_r05_tp_sp_fsdp"].pin_zero == \
        ("SPMD001",)


def test_pinned_codes_fail_even_when_baselined():
    """pin_zero outranks the ratchet: a baselined SPMD001 on a
    pinned target still fails. Synthetic records — no compile."""
    rec = {
        "target": "multichip_r05_tp_sp_fsdp",
        "title": "t", "devices": 8, "strategy": "tp", "mesh": {},
        "spmd_reshard_warnings": 1,
        "findings": [_f("SPMD001:multichip_r05_tp_sp_fsdp:x")],
        "findings_by_code": {"SPMD001": 1},
        "collectives": {},
    }
    doc = audit.assemble_doc([rec])
    (violation,) = audit.pinned_violations(doc)
    assert "SPMD001" in violation and "ZERO" in violation
    # A non-pinned code rides the ratchet as before.
    rec2 = dict(rec, findings=[_f("SPMD002:multichip_r05_tp_sp_fsdp:y")],
                findings_by_code={"SPMD002": 1},
                spmd_reshard_warnings=0)
    assert audit.pinned_violations(audit.assemble_doc([rec2])) == []


def test_auditor_headline_config_is_clean(headline_report):
    r = headline_report
    assert r["findings"] == []
    assert r["spmd_reshard_warnings"] == 0
    assert r["collectives"]["total_collectives"] == 0


def test_committed_baseline_is_exactly_current(tp_sp_fsdp_report,
                                               headline_report):
    """The ratchet contract on HEAD: every current finding is known
    (no red CI on a clean tree) and no baseline entry is stale (no
    dead suppressions hiding future regressions)."""
    findings = (tp_sp_fsdp_report["findings"]
                + headline_report["findings"])
    cmp = baseline.compare(findings, baseline.load())
    assert not cmp["new"], [f["fingerprint"] for f in cmp["new"]]
    assert not cmp["stale"], cmp["stale"]


def test_new_finding_would_fail_check(tp_sp_fsdp_report):
    """Ratchet end-to-end: drop one baselined fingerprint and the
    same findings produce a NEW entry — what --check exits 1 on."""
    base = baseline.load()
    trimmed = {"schema": baseline.SCHEMA,
               "fingerprints": base["fingerprints"][1:]}
    cmp = baseline.compare(tp_sp_fsdp_report["findings"], trimmed)
    assert len(cmp["new"]) == 1


def test_audit_targets_document_shape(tp_sp_fsdp_report):
    """spmd_audit.json contract: schema 1, per-target records with
    findings + collective summaries, totals consistent, and the
    rendered report tagging findings against the baseline. Assembled
    from the module-scoped record — no recompile."""
    doc = audit.assemble_doc([tp_sp_fsdp_report])
    assert doc["schema"] == 1
    (rec,) = doc["targets"]
    assert rec["target"] == "multichip_r05_tp_sp_fsdp"
    assert rec["mesh"] == {"fsdp": 2, "sp": 2, "tp": 2}
    assert doc["totals"]["findings"] == len(rec["findings"])
    # SPMD001 fixed (and pinned); the ring's permutes remain known.
    assert doc["totals"]["by_code"].get("SPMD001", 0) == 0
    assert doc["totals"]["by_code"].get("SPMD002", 0) >= 1
    # Render must tag known findings against the committed baseline.
    cmp = baseline.compare(audit.all_findings(doc), baseline.load(),
                           targets=[rec["target"]])
    lines = "\n".join(audit.render_report(doc, cmp))
    assert "[known]" in lines and "SPMD002" in lines


# ---------------------------------------------------------------------------
# Trainer satellite: the collectives event carries the reshard count
# ---------------------------------------------------------------------------


def test_trainer_collectives_report_carries_reshard_count():
    from distributed_training_tpu.analysis.compile import (
        build_abstract_trainer)
    from distributed_training_tpu.telemetry.collectives import (
        SUMMARY_KEYS, summary_of_event)
    trainer, _rt, batch = build_abstract_trainer(
        2, "ddp", "transformer",
        dict(vocab_size=64, d_model=16, n_heads=2, n_layers=1,
             max_seq_len=8, dtype="float32"),
        batch_size=2, seq_len=8,
        train_overrides=dict(min_shard_elems=1, dtype="float32"))
    rep = trainer.collectives_report(batch)
    assert rep["spmd_reshard_warnings"] == 0
    assert "spmd_reshard_warnings" in SUMMARY_KEYS
    assert summary_of_event(rep)["spmd_reshard_warnings"] == 0


# ---------------------------------------------------------------------------
# CLI guards (cheap arg-validation paths; the full --check subprocess
# runs once, in tests/test_lint_local.py, as the tier-1 gate)
# ---------------------------------------------------------------------------


def test_cli_write_baseline_refuses_target_subset():
    """A subset run must never rewrite the committed baseline — the
    unselected targets' known fingerprints would vanish and the next
    full --check would fail on them as NEW."""
    out = subprocess.run(
        [sys.executable, "-m", "distributed_training_tpu.analysis",
         "--no-rules", "--targets", "single_chip_headline",
         "--write-baseline"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "full run" in out.stderr
