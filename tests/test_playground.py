"""Playground tests: the formalization of the reference's
convergence-by-inspection and determinism mechanisms (SURVEY.md §4.2-4.3)
— replica identity, grad-sync equivalence, convergence."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_tpu.playground.ddp_from_primitives import (
    init_params, main, make_dataset, mse_loss, train_ddp,
)


def test_converges_and_replicas_stay_identical():
    result = train_ddp(world_size=4, epochs=4, batch_size=16,
                       lr=0.05, dataset_size=256, seed=42)
    hist = result["history"]
    assert hist[-1]["mean_loss"] < hist[0]["mean_loss"]
    # params came out of shard_map with out_specs=P() — all-replica
    # identical by construction; check they're finite and updated
    p = result["params"]
    assert np.isfinite(np.asarray(p["w"])).all()


def test_matches_single_device_training():
    """DDP over 8 ranks with grad-mean == single-device training on the
    full batch (the definition of data parallelism). Same seed, same
    data order, same lr -> identical params."""
    ws, bs, lr, n = 8, 8, 0.05, 128
    ddp = train_ddp(world_size=ws, epochs=2, batch_size=bs, lr=lr,
                    dataset_size=n, seed=7)

    # reproduce on one device: global batch = ws * bs rows in shard-major
    # order (exactly how train_ddp assembles xb/yb)
    from distributed_training_tpu.data.sampler import (
        DistributedShardSampler,
    )
    params = init_params(jax.random.PRNGKey(7))
    x, y = make_dataset(n, seed=7)
    sampler = DistributedShardSampler(n, ws, shuffle=True, seed=7)
    grad_fn = jax.jit(jax.grad(mse_loss))
    for epoch in range(2):
        sampler.set_epoch(epoch)
        shard_idx = np.stack([sampler.shard_indices(r)
                              for r in range(ws)])
        for s in range(sampler.num_samples // bs):
            rows = shard_idx[:, s * bs:(s + 1) * bs].reshape(-1)
            # mean-of-per-shard-means == global mean when shards are
            # equal-sized, so a single full-batch grad matches
            g = grad_fn(params, jnp.asarray(x[rows]),
                        jnp.asarray(y[rows]))
            params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)

    np.testing.assert_allclose(np.asarray(ddp["params"]["w"]),
                               np.asarray(params["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ddp["params"]["b"]),
                               np.asarray(params["b"]),
                               rtol=1e-5, atol=1e-6)


def test_norm_logging_and_rank_files(tmp_path):
    log_dir = str(tmp_path / "logs")
    train_ddp(world_size=2, epochs=1, batch_size=16, dataset_size=64,
              log_norms=True, log_dir=log_dir)
    files = sorted((tmp_path / "logs").iterdir())
    assert [f.name for f in files] == ["ddp_rank_0.log", "ddp_rank_1.log"]
    txt0, txt1 = files[0].read_text(), files[1].read_text()
    assert "local_loss" in txt0 and "|g[" in txt0
    # per-rank values must actually be per-rank (regression: out_specs
    # P() used to collapse them to one replica's value)
    loss0 = [ln.split("local_loss=")[1].split()[0]
             for ln in txt0.splitlines()]
    loss1 = [ln.split("local_loss=")[1].split()[0]
             for ln in txt1.splitlines()]
    assert loss0 != loss1


def test_cli(tmp_path, capsys):
    assert main(["--world-size", "2", "--epochs", "1",
                 "--dataset-size", "64", "--batch-size", "16",
                 "--log-dir", str(tmp_path / "logs")]) == 0
    assert "final mean_loss" in capsys.readouterr().out


def test_world_size_validation():
    import pytest
    with pytest.raises(ValueError):
        train_ddp(world_size=100)
