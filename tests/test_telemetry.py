"""Telemetry subsystem: span nesting + events.jsonl schema, goodput
ledger accounting, hang watchdog postmortems, HBM sampling, the
summarizer CLI, and the end-to-end CPU demo (trainer wiring: a tiny
run must produce metrics.jsonl + events.jsonl + a goodput report whose
buckets sum to wall-clock)."""

import json
import os
import time

import numpy as np
import pytest

from distributed_training_tpu import telemetry
from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models import build_model
from distributed_training_tpu.telemetry.goodput import GoodputLedger
from distributed_training_tpu.telemetry.hbm import HBMSampler
from distributed_training_tpu.telemetry.watchdog import (
    HangWatchdog, arm_process_watchdog, write_postmortem)
from distributed_training_tpu.train.trainer import Trainer


@pytest.fixture(autouse=True)
def _fresh_ambient():
    """Ambient telemetry is process state (like the root logger);
    every test starts and ends uninstalled."""
    telemetry.uninstall()
    yield
    telemetry.uninstall()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- spans / events --------------------------------------------------------


def test_spans_nest_and_record_depth_parent(tmp_path):
    path = str(tmp_path / "events.jsonl")
    t = telemetry.Telemetry(events_jsonl=path)
    with t.span("outer"):
        with t.span("inner", step=3):
            pass
    rows = _read_jsonl(path)
    assert rows[0]["kind"] == "run_start"
    inner, outer = rows[1], rows[2]  # inner closes first
    assert (inner["name"], inner["depth"], inner["parent"]) == \
        ("inner", 1, "outer")
    assert inner["step"] == 3
    assert (outer["name"], outer["depth"], outer["parent"]) == \
        ("outer", 0, None)
    assert outer["dur_s"] >= inner["dur_s"] >= 0


def test_span_reentrant_after_exception(tmp_path):
    t = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"))
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    # The stack must unwind: a following span is depth 0 again.
    with t.span("after"):
        pass
    rows = _read_jsonl(str(tmp_path / "e.jsonl"))
    assert rows[-1]["name"] == "after" and rows[-1]["depth"] == 0


def test_ambient_span_is_noop_until_installed(tmp_path):
    with telemetry.span("nobody-listening"):
        pass  # must not raise, must not write anywhere
    path = str(tmp_path / "events.jsonl")
    telemetry.install(telemetry.Telemetry(events_jsonl=path))
    with telemetry.span("recorded"):
        pass
    telemetry.event("ping", n=1)
    names = [r.get("name", r["kind"]) for r in _read_jsonl(path)]
    assert names == ["run_start", "recorded", "ping"]


def test_tail_is_bounded(tmp_path):
    t = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"),
                            tail_events=4)
    for i in range(10):
        t.event("tick", i=i)
    tail = t.tail()
    assert len(tail) == 4 and tail[-1]["i"] == 9


def test_nan_fields_sanitized(tmp_path):
    path = str(tmp_path / "e.jsonl")
    t = telemetry.Telemetry(events_jsonl=path)
    t.event("stats", value=float("nan"))
    assert _read_jsonl(path)[-1]["value"] is None


def test_close_stops_recording_keeps_tail(tmp_path):
    path = str(tmp_path / "e.jsonl")
    t = telemetry.Telemetry(events_jsonl=path)
    t.event("before", i=1)
    t.close()
    t.close()  # idempotent
    t.event("after", i=2)  # no-op, must not raise on a closed handle
    assert [r["kind"] for r in _read_jsonl(path)] == \
        ["run_start", "before"]
    assert t.tail()[-1]["kind"] == "before"


def test_fresh_false_appends_not_truncates(tmp_path):
    """The resume/eval path: fresh=False must append after a run_start
    marker, never wipe the training run's stream."""
    path = str(tmp_path / "e.jsonl")
    t1 = telemetry.Telemetry(events_jsonl=path)
    t1.event("train_era", i=1)
    t1.close()
    t2 = telemetry.Telemetry(events_jsonl=path, fresh=False)
    t2.event("eval_era", i=2)
    kinds = [r["kind"] for r in _read_jsonl(path)]
    assert kinds == ["run_start", "train_era", "run_start", "eval_era"]


# -- goodput ledger --------------------------------------------------------


def test_ledger_buckets_sum_to_wall_clock(tmp_path):
    t = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"))
    ledger = GoodputLedger(flops_per_step=1e6, num_devices=2,
                           peak_flops=1e9)
    t.attach_ledger(ledger)
    ledger.reset()
    wall0 = time.perf_counter()
    with t.span("compile"):
        time.sleep(0.03)
    for _ in range(3):
        with t.span("data_wait"):
            time.sleep(0.005)
        with t.span("step"):
            time.sleep(0.02)
    with t.span("ckpt_save"):
        time.sleep(0.01)
    time.sleep(0.02)  # untracked -> idle
    rep = ledger.report()
    wall = time.perf_counter() - wall0
    b = rep["buckets"]
    # Tracked + idle sums to the ledger's wall exactly (idle is
    # derived); the ledger's wall tracks the external clock.
    assert sum(b.values()) == pytest.approx(rep["wall_s"], rel=0.02)
    assert rep["wall_s"] == pytest.approx(wall, rel=0.05, abs=0.02)
    assert rep["steps"] == 3
    assert b["compile"] >= 0.03 and b["checkpoint"] >= 0.01
    assert b["idle"] >= 0.015
    assert 0 < rep["goodput"] < 1
    # MFU arithmetic: steps * flops / (wall * devices * peak).
    assert rep["mfu_wall"] == pytest.approx(
        3 * 1e6 / (rep["wall_s"] * 2 * 1e9), rel=0.01)


def test_nested_span_does_not_double_count(tmp_path):
    t = telemetry.Telemetry(events_jsonl=str(tmp_path / "e.jsonl"))
    ledger = GoodputLedger()
    t.attach_ledger(ledger)
    with t.span("step"):
        with t.span("ckpt_save"):  # nested: events-only
            time.sleep(0.01)
    rep = ledger.report()
    assert rep["buckets"]["checkpoint"] == 0.0
    assert rep["buckets"]["step"] >= 0.01


def test_window_report_resets(tmp_path):
    ledger = GoodputLedger()
    ledger.add("step", 0.5, steps=1)
    w1 = ledger.window_report()
    assert w1["buckets"]["step"] == 0.5 and w1["steps"] == 1
    w2 = ledger.window_report()
    assert w2["buckets"]["step"] == 0.0 and w2["steps"] == 0
    # The cumulative report still carries everything.
    assert ledger.report()["buckets"]["step"] == 0.5


# -- watchdog --------------------------------------------------------------


def _postmortem_complete(path):
    names = set(os.listdir(path))
    return {"meta.json", "stacks.txt", "events_tail.jsonl",
            "memory_stats.json"} <= names


def test_watchdog_fires_on_stall_and_writes_postmortem(tmp_path):
    tel = telemetry.Telemetry(
        events_jsonl=str(tmp_path / "e.jsonl"))
    tel.event("before_stall", step=7)
    wd = HangWatchdog(0.15, str(tmp_path / "pm"), telemetry=tel,
                      poll_s=0.02)
    try:
        wd.arm(step=7)
        time.sleep(0.6)  # the "stalled step"
    finally:
        wd.stop()
    assert wd.fired_path and _postmortem_complete(wd.fired_path)
    meta = json.load(open(os.path.join(wd.fired_path, "meta.json")))
    assert meta["step"] == 7 and meta["watchdog_timeout_s"] == 0.15
    stacks = open(os.path.join(wd.fired_path, "stacks.txt")).read()
    assert "Thread" in stacks or "Stack" in stacks
    tail = _read_jsonl(os.path.join(wd.fired_path,
                                    "events_tail.jsonl"))
    assert any(r.get("kind") == "before_stall" for r in tail)
    # The firing itself is in the event stream.
    kinds = [r["kind"] for r in _read_jsonl(str(tmp_path / "e.jsonl"))]
    assert "watchdog_fired" in kinds


def test_watchdog_disarm_prevents_firing(tmp_path):
    wd = HangWatchdog(0.1, str(tmp_path / "pm"), poll_s=0.02)
    try:
        wd.arm(step=1)
        time.sleep(0.04)
        wd.disarm()
        time.sleep(0.3)
    finally:
        wd.stop()
    assert wd.fired_path is None
    assert not os.path.exists(str(tmp_path / "pm"))


def test_watchdog_per_arm_timeout_override(tmp_path):
    # The trainer gives the compile step a larger allowance; an armed
    # override must be honored for that arm only.
    wd = HangWatchdog(0.05, str(tmp_path / "pm"), poll_s=0.02)
    try:
        wd.arm(step=1, timeout_s=1.0)
        time.sleep(0.2)  # beyond default, inside override: no fire
        assert wd.fired_path is None
        wd.arm(step=2)
        time.sleep(0.25)  # default applies again: fires
    finally:
        wd.stop()
    assert wd.fired_path is not None


def test_write_postmortem_unique_dirs(tmp_path):
    p1 = write_postmortem(str(tmp_path), "first")
    p2 = write_postmortem(str(tmp_path), "second")
    assert p1 != p2 and _postmortem_complete(p1) \
        and _postmortem_complete(p2)


def test_arm_process_watchdog_cancel_removes_bundle(tmp_path):
    cancel = arm_process_watchdog(30.0, str(tmp_path / "pm"), "probe")
    assert os.listdir(str(tmp_path / "pm"))
    cancel()
    cancel()  # idempotent (also registered atexit — must not double-act)
    assert os.listdir(str(tmp_path / "pm")) == []


def test_arm_process_watchdog_keeps_fired_bundle(tmp_path):
    """A dump that actually fired is evidence: cancel() (explicit or
    via atexit) must keep it, not delete it."""
    cancel = arm_process_watchdog(0.2, str(tmp_path / "pm"), "probe")
    time.sleep(0.6)  # the faulthandler dump fires
    cancel()
    (bundle,) = os.listdir(str(tmp_path / "pm"))
    stacks = open(os.path.join(str(tmp_path / "pm"), bundle,
                               "stacks.txt")).read()
    assert stacks.strip()


# -- hbm sampler -----------------------------------------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_hbm_sampler_cadence_and_schema(tmp_path):
    path = str(tmp_path / "e.jsonl")
    tel = telemetry.Telemetry(events_jsonl=path)
    devices = [_FakeDevice({"bytes_in_use": 10, "peak_bytes_in_use": 99,
                            "irrelevant_counter": 5}),
               _FakeDevice(None),
               _FakeDevice(RuntimeError("backend wedged"))]
    s = HBMSampler(tel, every=2, estimate_bytes=123, devices=devices)
    s.maybe_sample(1)   # off cadence
    s.maybe_sample(2)   # samples
    rows = [r for r in _read_jsonl(path) if r["kind"] == "hbm"]
    assert len(rows) == 1
    rec = rows[0]
    assert rec["step"] == 2 and rec["estimate_bytes"] == 123
    d0, d1, d2 = rec["devices"]
    assert d0["stats"] == {"bytes_in_use": 10, "peak_bytes_in_use": 99}
    assert d1["stats"] is None                # CPU-style backend
    assert "backend wedged" in d2["error"]    # never raises


# -- summarizer CLI --------------------------------------------------------


def _synthetic_run_dir(tmp_path):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with open(run_dir / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"run_start": True, "step": 0}) + "\n")
        f.write(json.dumps({"epoch": 0, "step": 1, "loss": 2.0,
                            "warmup": True}) + "\n")
        for i, loss in ((2, 1.5), (3, 1.0)):
            f.write(json.dumps(
                {"epoch": 0, "step": i, "loss": loss,
                 "steps_per_sec": 10.0,
                 "samples_per_sec_per_chip": 40.0,
                 "mfu": 0.3 + i / 100}) + "\n")
        f.write("{torn line\n")  # crashed-writer tolerance
    with open(run_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_start", "t": 0.0,
                            "step": 0}) + "\n")
        for name, dur in (("compile", 2.0), ("data_wait", 0.1),
                          ("step", 0.5), ("step", 0.5)):
            f.write(json.dumps({"kind": "span", "name": name,
                                "t": 3.0, "dur_s": dur, "depth": 0,
                                "parent": None}) + "\n")
        f.write(json.dumps(
            {"kind": "goodput", "scope": "run", "t": 4.0,
             "wall_s": 4.0, "steps": 2, "goodput": 0.25,
             "buckets": {"compile": 2.0, "data_wait": 0.1,
                         "step": 1.0, "checkpoint": 0.0,
                         "eval": 0.0, "idle": 0.9}}) + "\n")
        f.write(json.dumps(
            {"kind": "hbm", "t": 3.5, "step": 2, "estimate_bytes": 64,
             "devices": [{"id": 0, "stats":
                          {"peak_bytes_in_use": 2 ** 30}}]}) + "\n")
    (run_dir / "postmortem" / "x_pid1").mkdir(parents=True)
    return run_dir


def test_summarize_run_synthetic(tmp_path):
    from distributed_training_tpu.telemetry.summarize import (
        render, summarize_run)
    s = summarize_run(str(_synthetic_run_dir(tmp_path)))
    assert s["loss"]["first"] == 2.0 and s["loss"]["last"] == 1.0
    # warmup row excluded from trajectories
    assert s["mfu"]["first"] == pytest.approx(0.32)
    assert s["mfu"]["last"] == pytest.approx(0.33)
    assert s["goodput"]["goodput"] == 0.25
    assert s["hbm"]["peak_gib"] == 1.0
    assert s["postmortems"] == ["x_pid1"]
    text = render(s)
    assert "goodput" in text and "postmortem bundle" in text


def test_summarizer_cli_renders_and_json(tmp_path, capsys):
    from distributed_training_tpu.telemetry.summarize import main
    run_dir = str(_synthetic_run_dir(tmp_path))
    assert main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "loss: 2 -> 1" in out
    assert main([run_dir, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["metrics_rows"] == 4
    assert main([run_dir + "/nope"]) == 2


def test_summarizer_goodput_reconstructed_without_run_event(tmp_path):
    """A killed run writes no final report; the summarizer rebuilds
    the breakdown from depth-0 spans."""
    run_dir = tmp_path / "dead"
    run_dir.mkdir()
    with open(run_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_start", "t": 10.0}) + "\n")
        f.write(json.dumps({"kind": "span", "name": "step", "t": 12.0,
                            "dur_s": 1.5, "depth": 0}) + "\n")
    from distributed_training_tpu.telemetry.summarize import (
        summarize_run)
    gp = summarize_run(str(run_dir))["goodput"]
    assert gp["reconstructed"] and gp["wall_s"] == 2.0
    assert gp["buckets"]["step"] == 1.5
    assert gp["buckets"]["idle"] == pytest.approx(0.5)


def test_summarizer_fallback_wall_segments_per_run_start(tmp_path):
    """An eval (or resume) appended hours after a crash must not book
    the dead time between sessions as idle: wall is summed per
    run_start segment."""
    run_dir = tmp_path / "crashed_then_evaled"
    run_dir.mkdir()
    with open(run_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_start", "t": 100.0}) + "\n")
        f.write(json.dumps({"kind": "span", "name": "step", "t": 102.0,
                            "dur_s": 1.5, "depth": 0}) + "\n")
        # 10000s later: eval appends its own session.
        f.write(json.dumps({"kind": "run_start", "t": 10102.0}) + "\n")
        f.write(json.dumps({"kind": "span", "name": "eval",
                            "t": 10103.0, "dur_s": 1.0,
                            "depth": 0}) + "\n")
    from distributed_training_tpu.telemetry.summarize import (
        summarize_run)
    gp = summarize_run(str(run_dir))["goodput"]
    # wall = (102-100) + (10103-10102), NOT 10103-100.
    assert gp["wall_s"] == pytest.approx(3.0)
    assert gp["buckets"]["idle"] == pytest.approx(0.5)


# -- trainer wiring (the CPU demo, as a pinned test) -----------------------


def _demo_trainer(rt, tmp_path, **train_over):
    cfg = Config()
    cfg.train.batch_size = 4
    cfg.train.total_epochs = 2
    cfg.train.save_every = 1
    cfg.train.log_every = 2
    cfg.train.dataset_size = 32
    cfg.train.hbm_sample_every = 2
    cfg.train.metrics_jsonl = str(tmp_path / "run" / "metrics.jsonl")
    cfg.train.events_jsonl = str(tmp_path / "run" / "events.jsonl")
    for k, v in train_over.items():
        setattr(cfg.train, k, v)
    model = build_model("mlp", input_size=20, output_size=1,
                        loss="mse")
    ds = SyntheticRegressionDataset(size=32, in_dim=20, out_dim=1,
                                    seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=4)
    from distributed_training_tpu.checkpoint import Checkpointer
    ckpt = Checkpointer(str(tmp_path / "run" / "ckpt"))
    return cfg, model, loader, ckpt


def test_trainer_end_to_end_telemetry(cpu8, tmp_path):
    cfg, model, loader, ckpt = _demo_trainer(cpu8, tmp_path)
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    trainer = Trainer(cfg, cpu8, model, loader, ckpt)
    t0 = time.perf_counter()
    summary = trainer.train()
    wall = time.perf_counter() - t0
    assert np.isfinite(summary["mean_loss"])

    # Both streams exist and parse.
    metrics_rows = _read_jsonl(cfg.train.metrics_jsonl)
    events = _read_jsonl(cfg.train.events_jsonl)
    assert metrics_rows[0] == {"run_start": True, "step": 0}
    span_names = {e["name"] for e in events if e["kind"] == "span"}
    assert {"compile", "step", "data_wait", "data_assemble",
            "ckpt_save", "ckpt_wait"} <= span_names

    # The acceptance check: goodput buckets (incl. idle) sum to the
    # run's wall-clock within 5%, and wall matches reality.
    gp = summary["goodput"]
    assert sum(gp["buckets"].values()) == pytest.approx(
        gp["wall_s"], rel=0.05)
    assert gp["wall_s"] == pytest.approx(wall, rel=0.2, abs=0.5)
    assert gp["steps"] > 0 and gp["buckets"]["compile"] > 0
    assert gp["buckets"]["checkpoint"] > 0

    # Window reports on the log cadence + the final run report.
    scopes = [e["scope"] for e in events if e["kind"] == "goodput"]
    assert "window" in scopes and scopes[-1] == "run"

    # HBM samples on cadence (CPU backend: stats may be null, but the
    # cross-check estimate from utils/memory.py rides along).
    hbm = [e for e in events if e["kind"] == "hbm"]
    assert hbm and hbm[0]["estimate_bytes"] > 0

    # The summarizer renders the real run_dir without error.
    from distributed_training_tpu.telemetry.summarize import (
        render, summarize_run)
    text = render(summarize_run(str(tmp_path / "run")))
    assert "goodput" in text


def test_trainer_watchdog_fires_on_stalled_step(cpu8, tmp_path):
    """A deliberately-stalled step (slow _step_fn) must produce a
    complete postmortem bundle through the real training loop — and
    with abort=False training still completes."""
    # Two epochs = two steps: the FIRST step's compile allowance (10x)
    # covers the 0.5s stall; the second step runs at the 0.15s default
    # and must fire mid-stall.
    cfg, model, loader, ckpt = _demo_trainer(
        cpu8, tmp_path, total_epochs=2, save_every=0)
    tel = telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    wd = HangWatchdog(0.15, str(tmp_path / "run" / "postmortem"),
                      telemetry=tel, poll_s=0.02)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt, watchdog=wd)
    orig = trainer._step_fn

    def slow_step(state, batch, rng):
        time.sleep(0.5)  # > timeout, < the first-step 10x allowance...
        return orig(state, batch, rng)

    trainer._step_fn = slow_step
    try:
        summary = trainer.train()
    finally:
        wd.stop()
    assert np.isfinite(summary["mean_loss"])
    assert wd.fired_path and _postmortem_complete(wd.fired_path)
    events = _read_jsonl(cfg.train.events_jsonl)
    assert any(e["kind"] == "watchdog_fired" for e in events)


def test_trainer_watchdog_covers_data_wait(cpu8, tmp_path):
    """A wedged input pipeline (loader blocks, no batch arrives) is
    armed too: the watchdog must fire during the data fetch, not only
    during the step."""
    cfg, model, loader, ckpt = _demo_trainer(
        cpu8, tmp_path, total_epochs=2, save_every=0)
    tel = telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    wd = HangWatchdog(0.15, str(tmp_path / "run" / "postmortem"),
                      telemetry=tel, poll_s=0.02)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt, watchdog=wd)
    orig_epoch = trainer.loader.epoch

    def stalling_epoch(epoch):
        for i, batch in enumerate(orig_epoch(epoch)):
            if epoch > 0:
                time.sleep(0.6)  # the wedged-prefetch stand-in
            yield batch

    trainer.loader.epoch = stalling_epoch
    try:
        summary = trainer.train()
    finally:
        wd.stop()
    assert np.isfinite(summary["mean_loss"])
    assert wd.fired_path and _postmortem_complete(wd.fired_path)


def test_trainer_binds_telemetry_installed_after_construction(
        cpu8, tmp_path):
    """install() after Trainer() must still instrument the run: the
    trainer re-resolves the ambient sink at train() (a snapshot taken
    only at construction would silently bind the ledger and every
    trainer span to the null sink)."""
    # Two epochs = two steps (the global batch covers the dataset):
    # the first dispatch is the compile span, the second a step span.
    cfg, model, loader, ckpt = _demo_trainer(cpu8, tmp_path,
                                             total_epochs=2)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt)  # before install
    telemetry.install(telemetry.Telemetry(
        events_jsonl=cfg.train.events_jsonl))
    summary = trainer.train()
    assert "goodput" in summary
    events = _read_jsonl(cfg.train.events_jsonl)
    span_names = {e["name"] for e in events if e["kind"] == "span"}
    assert {"compile", "step", "data_wait"} <= span_names


def test_trainer_no_telemetry_still_trains(cpu8, tmp_path):
    """Uninstalled ambient telemetry: spans are pure trace
    annotations; no events file, no ledger in the summary."""
    cfg, model, loader, ckpt = _demo_trainer(cpu8, tmp_path,
                                             total_epochs=1)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])
    assert "goodput" not in summary
    assert not os.path.exists(cfg.train.events_jsonl)


# -- serving observability: histograms, trace schema, SLO report -----------


def test_serving_histograms_bucket_math():
    """The tenant-labeled latency histograms against hand-computed
    cumulative bucket counts: each observation lands in EVERY bucket
    whose bound admits it (Prometheus-cumulative), +Inf equals the
    count, and the sum is exact. The hand-computed nearest-rank p50
    must fall inside the first bucket whose cumulative count reaches
    rank — the quantile a scraper would reconstruct brackets the
    true one."""
    from distributed_training_tpu.telemetry.metrics_server import (
        HIST_BUCKETS, MetricsServer)

    ms = MetricsServer(0)
    ttfts = {"a": [0.004, 0.011, 0.011, 0.3], "b": [0.05]}
    for tenant, vs in ttfts.items():
        for v in vs:
            ms.observe({"kind": "serving_request", "tenant": tenant,
                        "id": "x", "ttft_s": v, "latency_s": 2 * v,
                        "queue_wait_s": 0.0, "new_tokens": 3})
    body = ms.render()
    fam = "dtt_serving_time_to_first_token_seconds"
    # Cumulative counts for tenant a over the pinned bounds.
    bounds = HIST_BUCKETS["serving_time_to_first_token_seconds"]
    want = {b: sum(1 for v in ttfts["a"] if v <= b) for b in bounds}
    assert want[0.005] == 1 and want[0.01] == 1 \
        and want[0.025] == 3 and want[0.25] == 3 and want[0.5] == 4
    for b, c in want.items():
        bs = str(int(b)) if b == int(b) else repr(float(b))
        assert f'{fam}_bucket{{tenant="a",le="{bs}"}} {c}' in body
    assert f'{fam}_bucket{{tenant="a",le="+Inf"}} 4' in body
    assert f'{fam}_count{{tenant="a"}} 4' in body
    sum_line = [ln for ln in body.splitlines()
                if ln.startswith(f'{fam}_sum{{tenant="a"}}')][0]
    assert float(sum_line.split()[-1]) == pytest.approx(0.326)
    # le is inclusive: 0.05 lands in the 0.05 bucket.
    assert f'{fam}_bucket{{tenant="b",le="0.05"}} 1' in body
    # Nearest-rank p50 of [0.004, 0.011, 0.011, 0.3] is 0.011; the
    # first bucket with cumulative count >= 2 is le=0.025 — the
    # scrape-side quantile estimate brackets the exact one.
    from distributed_training_tpu.telemetry.serving_trace import (
        percentile)
    exact = percentile(sorted(ttfts["a"]), 50)
    est_bucket = min(b for b, c in want.items() if c >= 2)
    assert exact == 0.011 and exact <= est_bucket == 0.025
    # The four families all carry the tenant label.
    for name in ("dtt_serving_e2e_seconds",
                 "dtt_serving_queue_wait_seconds",
                 "dtt_serving_tokens_per_request"):
        assert f'{name}_count{{tenant="a"}} 4' in body
        assert f"# TYPE {name} histogram" in body


def test_serving_trace_schema_keys_pinned():
    """The serving_trace record schema is pinned: additive keys only
    (TRACE_KEYS is the contract the offline analyzer and the span
    tests consume), and the aggregate stream schema stays 1."""
    from distributed_training_tpu.telemetry import aggregate
    from distributed_training_tpu.telemetry.serving_trace import (
        OUTCOMES, SPAN_EVENTS, TRACE_KEYS)

    assert TRACE_KEYS == (
        "id", "tenant", "outcome", "prompt_tokens", "new_tokens",
        "queue_wait_s", "ttft_s", "e2e_s", "prefix_hit_tokens",
        "tokens_discarded", "spans", "weights_versions")
    assert set(SPAN_EVENTS) == {
        "queued", "admitted", "resumed", "adopted", "prefill",
        "decode", "session_retain", "finished", "preempted"}
    assert OUTCOMES == ("finished", "preempted")
    assert aggregate.SCHEMA == 1


def _synthetic_serving_run(tmp_path):
    """A run dir whose events.jsonl holds hand-written serving_trace
    records with KNOWN latencies, so the report's nearest-rank
    percentiles and attainment fractions are exact pins."""
    run_dir = tmp_path / "srun"
    run_dir.mkdir()
    ttfts = {"chat": [0.01, 0.02, 0.03, 0.04, 0.05],
             "docs": [0.1, 0.2, 0.3, 0.4, 0.5]}
    with open(run_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_start", "t": 0.0,
                            "step": 0}) + "\n")
        i = 0
        for tenant, ts in ttfts.items():
            for ttft in ts:
                f.write(json.dumps(
                    {"kind": "serving_trace", "t": float(i),
                     "id": f"{tenant}-{i}", "tenant": tenant,
                     "outcome": "finished", "prompt_tokens": 8,
                     "new_tokens": 4, "queue_wait_s": 0.001,
                     "ttft_s": ttft, "e2e_s": ttft + 0.03,
                     "prefix_hit_tokens": 2, "tokens_discarded": 0,
                     "spans": [
                         {"ev": "queued", "t": 0.0},
                         {"ev": "admitted", "t": 0.001, "slot": 0},
                         {"ev": "prefill", "t": 0.005, "tokens": 8},
                         {"ev": "decode", "t": ttft, "emitted": 4},
                         {"ev": "finished", "t": ttft + 0.03},
                     ]}) + "\n")
                i += 1
        f.write(json.dumps(
            {"kind": "serving_trace", "t": float(i), "id": "chat-x",
             "tenant": "chat", "outcome": "preempted",
             "prompt_tokens": 8, "new_tokens": 2,
             "queue_wait_s": 0.001, "ttft_s": 0.01, "e2e_s": None,
             "prefix_hit_tokens": 0, "tokens_discarded": 2,
             "spans": [{"ev": "queued", "t": 0.0},
                       {"ev": "admitted", "t": 0.001, "slot": 1},
                       {"ev": "preempted", "t": 0.02,
                        "tokens_discarded": 2}]}) + "\n")
    return run_dir


def test_serving_report_cli_pinned(tmp_path, capsys):
    """`--serving-report` on the synthetic fixture: nearest-rank
    percentiles and SLO attainment are EXACT pins (chat n=5 ttfts
    10..50ms all inside the 250ms deadline; docs 100..500ms with
    only 100/200ms attaining), the preempted trace counts toward
    preemptions/retry cost but never toward attainment."""
    from distributed_training_tpu.telemetry.summarize import main

    run_dir = str(_synthetic_serving_run(tmp_path))
    assert main([run_dir, "--serving-report", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["traces"] == 11
    chat, docs = rep["tenants"]["chat"], rep["tenants"]["docs"]
    # Nearest-rank on n=5: p50 -> rank 3, p95/p99 -> rank 5.
    assert chat["ttft_s"]["p50"] == 0.03
    assert chat["ttft_s"]["p95"] == 0.05
    assert chat["ttft_s"]["p99"] == 0.05
    assert docs["ttft_s"]["p50"] == 0.3
    assert docs["ttft_s"]["p99"] == 0.5
    # conf deadlines: ttft 0.25, per-token 0.05 (decode tail 0.03
    # over 3 post-first tokens attains everywhere).
    assert chat["slo"] == {"attained": 1.0, "met": 5, "requests": 5,
                           "ttft_deadline_s": 0.25,
                           "per_token_deadline_s": 0.05}
    assert docs["slo"]["attained"] == pytest.approx(0.4)
    assert rep["overall"]["slo"]["attained"] == pytest.approx(0.7)
    assert rep["overall"]["slo"]["requests"] == 10
    assert chat["preemptions"] == 1
    assert chat["tokens_discarded"] == 2
    # Hit rate is over FINISHED prompts (20 hit / 80 prompt tokens);
    # the preempted trace's prompt never counts.
    assert rep["overall"]["prefix_hit_rate"] == pytest.approx(0.25)
    # CLI deadline override wins over the conf block.
    assert main([run_dir, "--serving-report", "--json",
                 "--slo-ttft-s", "0.15"]) == 0
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["tenants"]["docs"]["slo"]["attained"] == \
        pytest.approx(0.2)
    assert rep2["overall"]["slo"]["attained"] == \
        pytest.approx(0.6)
    # Human rendering names every tenant.
    assert main([run_dir, "--serving-report"]) == 0
    out = capsys.readouterr().out
    assert "chat" in out and "docs" in out
    # A run dir with no serving_trace records refuses politely.
    assert main([str(_synthetic_run_dir(tmp_path)),
                 "--serving-report"]) == 1


def test_summarizer_includes_serving_section(tmp_path):
    """The plain summarizer report grows a serving section when the
    run dir holds serving_trace records — same analyzer as the
    dedicated --serving-report path."""
    from distributed_training_tpu.telemetry.summarize import (
        render, summarize_run)

    s = summarize_run(str(_synthetic_serving_run(tmp_path)))
    assert s["serving"]["traces"] == 11
    assert "chat" in s["serving"]["tenants"]
    text = render(s)
    assert "serving" in text and "chat" in text
