"""Data layer tests: sampler fidelity to torch DistributedSampler
semantics, dataset determinism, sharded batch assembly."""

import numpy as np
import pytest

from distributed_training_tpu.data import (
    DistributedShardSampler, ShardedDataLoader, SyntheticLMDataset,
    SyntheticRegressionDataset, build_dataset,
)
from distributed_training_tpu.data.datasets import (
    MemmapTokenDataset, SyntheticImageDataset,
)


# --- sampler ---------------------------------------------------------------

def test_shards_partition_dataset_no_shuffle():
    s = DistributedShardSampler(16, 4, shuffle=False)
    all_idx = np.concatenate([s.shard_indices(i) for i in range(4)])
    assert sorted(all_idx) == list(range(16))
    # torch semantics: strided assignment rank::world
    np.testing.assert_array_equal(s.shard_indices(1), [1, 5, 9, 13])


def test_padding_wraps_like_torch():
    # N=10, 4 shards -> num_samples=3, total=12, pad with first 2 indices.
    s = DistributedShardSampler(10, 4, shuffle=False)
    assert s.num_samples == 3 and s.total_size == 12
    g = s.global_indices()
    np.testing.assert_array_equal(g, list(range(10)) + [0, 1])


def test_drop_last():
    s = DistributedShardSampler(10, 4, shuffle=False, drop_last=True)
    assert s.num_samples == 2 and s.total_size == 8
    g = s.global_indices()
    np.testing.assert_array_equal(g, list(range(8)))


def test_shuffle_identical_across_instances_and_reshuffles_per_epoch():
    # Identical on every process for a given (seed, epoch); different
    # across epochs (parity: sampler.set_epoch, distributed_trainer.py:175).
    a = DistributedShardSampler(100, 4, shuffle=True, seed=7)
    b = DistributedShardSampler(100, 4, shuffle=True, seed=7)
    a.set_epoch(3), b.set_epoch(3)
    np.testing.assert_array_equal(a.global_indices(), b.global_indices())
    b.set_epoch(4)
    assert not np.array_equal(a.global_indices(), b.global_indices())
    # still a permutation + pad
    assert sorted(b.global_indices()[:100]) == list(range(100))


def test_every_sample_covered_each_epoch_shuffled():
    s = DistributedShardSampler(33, 8, shuffle=True, seed=1)
    covered = np.concatenate([s.shard_indices(i) for i in range(8)])
    assert set(covered) == set(range(33))


def test_sampler_validation():
    with pytest.raises(ValueError):
        DistributedShardSampler(0, 4)
    with pytest.raises(ValueError):
        DistributedShardSampler(10, 0)
    with pytest.raises(ValueError):
        DistributedShardSampler(3, 8, drop_last=True)
    s = DistributedShardSampler(8, 4)
    with pytest.raises(ValueError):
        s.shard_indices(4)


# --- datasets --------------------------------------------------------------

def test_synthetic_regression_parity_shapes():
    ds = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1, seed=0)
    assert len(ds) == 2048
    b = ds.batch(np.array([0, 5, 7]))
    assert b["x"].shape == (3, 20) and b["y"].shape == (3, 1)
    assert b["x"].dtype == np.float32
    # uniform [0,1) like torch.rand (data_utils.py:10)
    assert 0 <= b["x"].min() and b["x"].max() < 1


def test_dataset_determinism():
    a = SyntheticRegressionDataset(size=64, seed=3)
    b = SyntheticRegressionDataset(size=64, seed=3)
    np.testing.assert_array_equal(a.columns["x"], b.columns["x"])


def test_lm_dataset():
    ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=100, seed=0)
    b = ds.batch(np.arange(4))
    assert b["tokens"].shape == (4, 17)
    assert b["tokens"].max() < 100


def test_image_dataset():
    ds = SyntheticImageDataset(size=8)
    b = ds.batch(np.arange(2))
    assert b["x"].shape == (2, 32, 32, 3) and b["y"].shape == (2,)


def test_doc_dataset_ragged_and_deterministic():
    from distributed_training_tpu.data.datasets import SyntheticDocDataset
    a = SyntheticDocDataset(size=16, min_len=3, max_len=9,
                            vocab_size=50, seed=4)
    b = SyntheticDocDataset(size=16, min_len=3, max_len=9,
                            vocab_size=50, seed=4)
    lens = {len(a.doc(i)) for i in range(16)}
    assert lens <= set(range(3, 10)) and len(lens) > 1
    np.testing.assert_array_equal(a.doc(5), b.doc(5))
    # map-style probe contract: zero-padded to the corpus max length
    probe = a.batch(np.array([0, 5]))
    assert probe["tokens"].shape == (2, 9)
    np.testing.assert_array_equal(
        probe["tokens"][1][:len(a.doc(5))], a.doc(5))


def test_memmap_tokens(tmp_path):
    path = str(tmp_path / "tokens.bin")
    np.arange(1000, dtype=np.uint16).tofile(path)
    ds = MemmapTokenDataset(path, seq_len=10)
    assert len(ds) == 99
    b = ds.batch(np.array([0, 1]))
    assert b["tokens"].shape == (2, 11)
    np.testing.assert_array_equal(b["tokens"][0], np.arange(11))
    np.testing.assert_array_equal(b["tokens"][1], np.arange(10, 21))


def test_registry():
    ds = build_dataset("synthetic", size=16)
    assert len(ds) == 16
    with pytest.raises(ValueError):
        build_dataset("nope")


# --- loader ----------------------------------------------------------------

def test_loader_global_batch_sharded(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False)
    assert dl.global_batch == 32
    assert dl.steps_per_epoch == 2  # 64/8 shards = 8 per shard / 4 = 2
    batches = list(dl.epoch(0))
    assert len(batches) == 2
    x = batches[0]["x"]
    assert x.shape == (32, 20)
    assert len(x.sharding.device_set) == 8


def test_loader_content_matches_sampler(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False,
                           prefetch_depth=0)
    batch = next(iter(dl.epoch(0)))
    x = np.asarray(batch["x"])
    # shard s rows [s*4,(s+1)*4) == dataset rows s, s+8, s+16, s+24
    for s in range(8):
        expected = ds.columns["x"][np.array([s, s + 8, s + 16, s + 24])]
        np.testing.assert_array_equal(x[s * 4:(s + 1) * 4], expected)


def test_loader_wrap_padding_final_batch(cpu8):
    # 40 samples / 8 shards = 5 per shard; batch 4 -> 2 steps, second
    # batch wrap-padded to full shape.
    ds = SyntheticRegressionDataset(size=40, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False)
    batches = list(dl.epoch(0))
    assert len(batches) == 2
    assert batches[1]["x"].shape == (32, 20)


def test_loader_epoch_reshuffles(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=8, shuffle=True, seed=5)
    b0 = np.asarray(next(iter(dl.epoch(0)))["x"])
    b1 = np.asarray(next(iter(dl.epoch(1)))["x"])
    assert not np.array_equal(b0, b1)


def test_loader_max_steps(cpu8):
    ds = SyntheticRegressionDataset(size=512, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, max_steps_per_epoch=3)
    assert len(list(dl.epoch(0))) == 3


def test_prefetch_propagates_errors(cpu8):
    from distributed_training_tpu.data.loader import _prefetch

    def bad():
        yield 1
        raise RuntimeError("boom")

    it = _prefetch(bad(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def _prefetch_threads():
    import threading
    return [t for t in threading.enumerate()
            if t.name == "data-prefetch" and t.is_alive()]


def test_half_consumed_epoch_leaves_no_producer_thread(cpu8):
    """A consumer that stops early (preemption, epoch cap, crash) must
    not strand the prefetch worker blocked on a full queue: closing
    the epoch iterator signals stop, drains, and JOINS the thread."""
    ds = SyntheticRegressionDataset(size=512, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, prefetch_depth=2)
    it = iter(dl.epoch(0))
    next(it)  # worker alive, queue filling
    assert _prefetch_threads()
    it.close()
    assert not _prefetch_threads(), \
        "prefetch worker leaked after early consumer exit"


def test_prefetch_worker_joined_on_gc(cpu8):
    """Dropping the iterator (the crash-unwind shape) must also stop
    the worker via the generator finalizer."""
    ds = SyntheticRegressionDataset(size=512, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, prefetch_depth=2)
    it = iter(dl.epoch(0))
    next(it)
    del it
    import gc
    gc.collect()
    assert not _prefetch_threads()


def test_assemble_probes_row0_once(cpu8):
    """The column spec (names/shapes/dtypes) is learned from ONE probe
    and cached — re-probing row 0 per step doubles IO on a
    remote/memmap source."""

    class CountingDataset:
        def __init__(self, base):
            self.base = base
            self.single_row_calls = 0

        def __len__(self):
            return len(self.base)

        def batch(self, idx):
            if len(idx) == 1:
                self.single_row_calls += 1
            return self.base.batch(idx)

    ds = CountingDataset(SyntheticRegressionDataset(size=64, seed=0))
    dl = ShardedDataLoader(ds, cpu8, batch_size=2, shuffle=False,
                           prefetch_depth=0)
    assert dl.steps_per_epoch == 4
    list(dl.epoch(0))
    list(dl.epoch(1))
    assert ds.single_row_calls == 1


# --- checkpointable position (exactly-once resume; data/stream.py has
# --- the multi-source properties) ------------------------------------------


def test_loader_state_tracks_consumption(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    assert dl.state_dict()["samples_consumed"] == 0
    it = iter(dl.epoch(0))
    next(it), next(it)
    it.close()
    st = dl.state_dict()
    assert (st["epoch"], st["step_in_epoch"]) == (0, 2)
    assert st["samples_consumed"] == 2 * dl.global_batch
    # A fully consumed epoch normalizes to the next epoch's boundary.
    list(dl.epoch(1))
    st = dl.state_dict()
    assert (st["epoch"], st["step_in_epoch"]) == (2, 0)


def test_loader_mid_epoch_resume_is_exactly_once(cpu8):
    """save → restore in a NEW loader → continue yields exactly the
    uninterrupted epoch's remaining batches (same rows, same order)."""
    import json
    ds = SyntheticRegressionDataset(size=64, seed=0)
    ref = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    want = [np.asarray(b["x"]) for b in ref.epoch(1)]

    a = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    it = iter(a.epoch(1))
    got = [np.asarray(next(it)["x"])]
    state = json.loads(json.dumps(a.state_dict()))
    it.close()

    b = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    b.load_state_dict(state)
    assert b.resume_epoch == 1
    got.extend(np.asarray(x["x"]) for x in b.epoch(1))
    assert len(got) == len(want)
    for x, y in zip(got, want):
        np.testing.assert_array_equal(x, y)


def test_loader_state_geometry_change_mid_epoch_raises(cpu8):
    """A changed steps_per_epoch makes a mid-epoch offset meaningless:
    raising routes the trainer to its replay-the-epoch fallback
    (silently skipping the remainder would drop data). Epoch-boundary
    positions survive geometry changes."""
    ds = SyntheticRegressionDataset(size=64, seed=0)
    a = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    mid = a.state_dict()
    list(a.epoch(1))  # brings position to the epoch-2 boundary
    boundary = a.state_dict()
    b = ShardedDataLoader(ds, cpu8, batch_size=4, seed=3)  # spe 4 -> 2
    with pytest.raises(ValueError, match="steps_per_epoch"):
        b.load_state_dict(mid)
    b.load_state_dict(boundary)
    assert b.resume_epoch == 2


def test_loader_state_rejects_shuffle_change(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    a = ShardedDataLoader(ds, cpu8, batch_size=2, shuffle=True, seed=3)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    state = a.state_dict()
    b = ShardedDataLoader(ds, cpu8, batch_size=2, shuffle=False, seed=3)
    with pytest.raises(ValueError, match="shuffle"):
        b.load_state_dict(state)


def test_loader_state_rejects_foreign_impl(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=2)
    with pytest.raises(ValueError, match="unsupported"):
        dl.load_state_dict({"schema": 1, "impl": "stream"})


def test_loader_state_rejects_world_change_mid_epoch(cpu8):
    """The strided per-epoch deal is a function of num_shards: the
    same global batch over a different world assigns different rows
    to each step, so a mid-epoch offset is not transferable across an
    elastic resize (epoch boundaries are)."""
    from distributed_training_tpu.runtime import fake_cpu_runtime
    ds = SyntheticRegressionDataset(size=64, seed=0)
    a = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    mid = a.state_dict()
    list(a.epoch(1))
    boundary = a.state_dict()
    # world 8 -> 4 at the same global batch: spe coincides, rows don't.
    b = ShardedDataLoader(ds, fake_cpu_runtime(4), batch_size=4, seed=3)
    assert b.steps_per_epoch == a.steps_per_epoch
    with pytest.raises(ValueError, match="num_shards|batch_size"):
        b.load_state_dict(mid)
    b.load_state_dict(boundary)
    assert b.resume_epoch == 2


def test_loader_state_rejects_seed_change(cpu8):
    """A changed train.seed reshuffles every epoch: resuming at the
    saved OFFSET of a different permutation would silently skip and
    replay rows while the cursor math still claims exactly-once."""
    ds = SyntheticRegressionDataset(size=64, seed=0)
    a = ShardedDataLoader(ds, cpu8, batch_size=2, seed=3)
    it = iter(a.epoch(0))
    next(it)
    it.close()
    state = a.state_dict()
    assert state["mid_epoch"] is True
    b = ShardedDataLoader(ds, cpu8, batch_size=2, seed=4)
    with pytest.raises(ValueError, match="seed"):
        b.load_state_dict(state)
