"""Data layer tests: sampler fidelity to torch DistributedSampler
semantics, dataset determinism, sharded batch assembly."""

import numpy as np
import pytest

from distributed_training_tpu.data import (
    DistributedShardSampler, ShardedDataLoader, SyntheticLMDataset,
    SyntheticRegressionDataset, build_dataset,
)
from distributed_training_tpu.data.datasets import (
    MemmapTokenDataset, SyntheticImageDataset,
)


# --- sampler ---------------------------------------------------------------

def test_shards_partition_dataset_no_shuffle():
    s = DistributedShardSampler(16, 4, shuffle=False)
    all_idx = np.concatenate([s.shard_indices(i) for i in range(4)])
    assert sorted(all_idx) == list(range(16))
    # torch semantics: strided assignment rank::world
    np.testing.assert_array_equal(s.shard_indices(1), [1, 5, 9, 13])


def test_padding_wraps_like_torch():
    # N=10, 4 shards -> num_samples=3, total=12, pad with first 2 indices.
    s = DistributedShardSampler(10, 4, shuffle=False)
    assert s.num_samples == 3 and s.total_size == 12
    g = s.global_indices()
    np.testing.assert_array_equal(g, list(range(10)) + [0, 1])


def test_drop_last():
    s = DistributedShardSampler(10, 4, shuffle=False, drop_last=True)
    assert s.num_samples == 2 and s.total_size == 8
    g = s.global_indices()
    np.testing.assert_array_equal(g, list(range(8)))


def test_shuffle_identical_across_instances_and_reshuffles_per_epoch():
    # Identical on every process for a given (seed, epoch); different
    # across epochs (parity: sampler.set_epoch, distributed_trainer.py:175).
    a = DistributedShardSampler(100, 4, shuffle=True, seed=7)
    b = DistributedShardSampler(100, 4, shuffle=True, seed=7)
    a.set_epoch(3), b.set_epoch(3)
    np.testing.assert_array_equal(a.global_indices(), b.global_indices())
    b.set_epoch(4)
    assert not np.array_equal(a.global_indices(), b.global_indices())
    # still a permutation + pad
    assert sorted(b.global_indices()[:100]) == list(range(100))


def test_every_sample_covered_each_epoch_shuffled():
    s = DistributedShardSampler(33, 8, shuffle=True, seed=1)
    covered = np.concatenate([s.shard_indices(i) for i in range(8)])
    assert set(covered) == set(range(33))


def test_sampler_validation():
    with pytest.raises(ValueError):
        DistributedShardSampler(0, 4)
    with pytest.raises(ValueError):
        DistributedShardSampler(10, 0)
    with pytest.raises(ValueError):
        DistributedShardSampler(3, 8, drop_last=True)
    s = DistributedShardSampler(8, 4)
    with pytest.raises(ValueError):
        s.shard_indices(4)


# --- datasets --------------------------------------------------------------

def test_synthetic_regression_parity_shapes():
    ds = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1, seed=0)
    assert len(ds) == 2048
    b = ds.batch(np.array([0, 5, 7]))
    assert b["x"].shape == (3, 20) and b["y"].shape == (3, 1)
    assert b["x"].dtype == np.float32
    # uniform [0,1) like torch.rand (data_utils.py:10)
    assert 0 <= b["x"].min() and b["x"].max() < 1


def test_dataset_determinism():
    a = SyntheticRegressionDataset(size=64, seed=3)
    b = SyntheticRegressionDataset(size=64, seed=3)
    np.testing.assert_array_equal(a.columns["x"], b.columns["x"])


def test_lm_dataset():
    ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=100, seed=0)
    b = ds.batch(np.arange(4))
    assert b["tokens"].shape == (4, 17)
    assert b["tokens"].max() < 100


def test_image_dataset():
    ds = SyntheticImageDataset(size=8)
    b = ds.batch(np.arange(2))
    assert b["x"].shape == (2, 32, 32, 3) and b["y"].shape == (2,)


def test_memmap_tokens(tmp_path):
    path = str(tmp_path / "tokens.bin")
    np.arange(1000, dtype=np.uint16).tofile(path)
    ds = MemmapTokenDataset(path, seq_len=10)
    assert len(ds) == 99
    b = ds.batch(np.array([0, 1]))
    assert b["tokens"].shape == (2, 11)
    np.testing.assert_array_equal(b["tokens"][0], np.arange(11))
    np.testing.assert_array_equal(b["tokens"][1], np.arange(10, 21))


def test_registry():
    ds = build_dataset("synthetic", size=16)
    assert len(ds) == 16
    with pytest.raises(ValueError):
        build_dataset("nope")


# --- loader ----------------------------------------------------------------

def test_loader_global_batch_sharded(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False)
    assert dl.global_batch == 32
    assert dl.steps_per_epoch == 2  # 64/8 shards = 8 per shard / 4 = 2
    batches = list(dl.epoch(0))
    assert len(batches) == 2
    x = batches[0]["x"]
    assert x.shape == (32, 20)
    assert len(x.sharding.device_set) == 8


def test_loader_content_matches_sampler(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False,
                           prefetch_depth=0)
    batch = next(iter(dl.epoch(0)))
    x = np.asarray(batch["x"])
    # shard s rows [s*4,(s+1)*4) == dataset rows s, s+8, s+16, s+24
    for s in range(8):
        expected = ds.columns["x"][np.array([s, s + 8, s + 16, s + 24])]
        np.testing.assert_array_equal(x[s * 4:(s + 1) * 4], expected)


def test_loader_wrap_padding_final_batch(cpu8):
    # 40 samples / 8 shards = 5 per shard; batch 4 -> 2 steps, second
    # batch wrap-padded to full shape.
    ds = SyntheticRegressionDataset(size=40, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False)
    batches = list(dl.epoch(0))
    assert len(batches) == 2
    assert batches[1]["x"].shape == (32, 20)


def test_loader_epoch_reshuffles(cpu8):
    ds = SyntheticRegressionDataset(size=64, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=8, shuffle=True, seed=5)
    b0 = np.asarray(next(iter(dl.epoch(0)))["x"])
    b1 = np.asarray(next(iter(dl.epoch(1)))["x"])
    assert not np.array_equal(b0, b1)


def test_loader_max_steps(cpu8):
    ds = SyntheticRegressionDataset(size=512, seed=0)
    dl = ShardedDataLoader(ds, cpu8, batch_size=4, max_steps_per_epoch=3)
    assert len(list(dl.epoch(0))) == 3


def test_prefetch_propagates_errors(cpu8):
    from distributed_training_tpu.data.loader import _prefetch

    def bad():
        yield 1
        raise RuntimeError("boom")

    it = _prefetch(bad(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)
