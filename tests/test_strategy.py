"""Strategy layer tests: spec inference, logical-axis routing, end-to-end
sharding placement on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_training_tpu.parallel import (
    DataParallel, FullyShardedDataParallel, TensorParallel, get_strategy,
    logical_to_spec,
)
from distributed_training_tpu.runtime import fake_cpu_runtime


def test_ddp_replicates_everything():
    s = DataParallel()
    assert s.param_spec((1024, 1024), None) == P()
    assert s.batch_spec() == P(("dp", "fsdp"))


def test_fsdp_shards_largest_divisible_dim():
    s = FullyShardedDataParallel(fsdp_size=4)
    assert s.param_spec((512, 128), None) == P("fsdp", None)
    assert s.param_spec((128, 512), None) == P(None, "fsdp")
    # not divisible -> replicated
    assert s.param_spec((130, 6), None) == P()
    # too small -> replicated (bias vectors etc.)
    assert s.param_spec((128,), None) == P()
    # ties pick the first dim
    assert s.param_spec((256, 256), None) == P("fsdp", None)


def test_fsdp_size_one_is_ddp():
    s = FullyShardedDataParallel(fsdp_size=1)
    assert s.param_spec((1 << 20, 8), None) == P()


def test_logical_to_spec_routing_and_conflicts():
    rules = {"vocab": "tp", "embed": "fsdp", "mlp": "tp"}
    assert logical_to_spec(("vocab", "embed"), rules) == P("tp", "fsdp")
    # same mesh axis twice -> second use dropped
    assert logical_to_spec(("mlp", "vocab"), rules) == P("tp")
    assert logical_to_spec((None, "embed"), rules) == P(None, "fsdp")
    assert logical_to_spec(("unknown",), rules) == P()


def test_tp_logical_routing():
    s = TensorParallel(fsdp_size=2, tp_size=4)
    # column-parallel mlp kernel (embed, mlp)
    assert s.param_spec((256, 1024), ("embed", "mlp")) == P("fsdp", "tp")
    # attention out proj (heads, head_dim, embed)
    assert s.param_spec((8, 64, 256), ("heads", None, "embed")) == \
        P("tp", None, "fsdp")


def test_specs_for_tree_with_eval_shape():
    s = FullyShardedDataParallel(fsdp_size=8)
    tree = {"w": jax.ShapeDtypeStruct((1024, 64), jnp.float32),
            "b": jax.ShapeDtypeStruct((64,), jnp.float32)}
    specs = s.specs_for_tree(tree)
    assert specs["w"] == P("fsdp", None)
    assert specs["b"] == P()


def test_shardings_place_params_on_mesh(cpu8):
    rt = fake_cpu_runtime(8, fsdp=8)
    s = get_strategy("fsdp", rt.spec)
    w = jnp.ones((1024, 32))
    sh = s.shardings_for_tree(rt.mesh, {"w": w})["w"]
    assert isinstance(sh, NamedSharding)
    placed = jax.device_put(w, sh)
    # each device holds 1/8 of the rows
    shard_shape = placed.sharding.shard_shape(placed.shape)
    assert shard_shape == (128, 32)


def test_fsdp_grad_matches_ddp_math(cpu8):
    """The semantic parity test: FSDP layout and DDP layout compute the
    same gradients for the same global batch (XLA inserts different
    collectives, math is identical)."""
    rt_ddp = fake_cpu_runtime(8)           # dp=8
    rt_fsdp = fake_cpu_runtime(8, fsdp=8)  # fsdp=8

    w = jnp.linspace(-1, 1, 256 * 8).reshape(256, 8)
    x = jnp.linspace(0, 1, 32 * 256).reshape(32, 256)
    y = jnp.ones((32, 8))

    def loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    grads = {}
    for tag, rt, strat in (("ddp", rt_ddp, get_strategy("ddp", rt_ddp.spec)),
                           ("fsdp", rt_fsdp,
                            get_strategy("fsdp", rt_fsdp.spec,
                                         min_shard_elems=1))):
        wp = jax.device_put(w, strat.shardings_for_tree(rt.mesh, w))
        xp = jax.device_put(x, NamedSharding(rt.mesh, strat.batch_spec()))
        yp = jax.device_put(y, NamedSharding(rt.mesh, strat.batch_spec()))
        g = jax.jit(jax.grad(loss))(wp, xp, yp)
        grads[tag] = np.asarray(g)
    np.testing.assert_allclose(grads["ddp"], grads["fsdp"], rtol=1e-5)


def test_registry():
    assert get_strategy("ddp").name == "ddp"
    assert get_strategy("hybrid").name == "fsdp"
    with pytest.raises(ValueError):
        get_strategy("zorp")


def test_zero1_warns_on_degenerate_data_size():
    """zero1 without a >1 data axis is silently plain DDP — the caller
    must be told the moment sharding is inactive (ADVICE r3)."""
    with pytest.warns(UserWarning, match="fully replicated"):
        s = get_strategy("zero1")
    assert s.name == "zero1"


def test_zero1_shards_moments_replicates_params(cpu8):
    """ZeRO-1: params replicated (DDP layout), Adam moments sharded
    over the data axes; the loss trajectory must be bit-identical to
    DDP (only the optimizer-state layout differs — XLA computes moment
    updates shard-wise and all-gathers the param delta)."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    trainers = {}
    for strat in ("ddp", "zero1"):
        rt = fake_cpu_runtime(8)  # dp=8
        cfg = Config()
        cfg.train.batch_size = 1
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.optimizer = "adamw"
        cfg.train.learning_rate = 0.01
        cfg.train.parallel_strategy = strat
        cfg.train.min_shard_elems = 1
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive"))
        ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=1, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[strat] = [float(trainer.train_step(b)["loss"])
                         for b in loader.epoch(0)]
        trainers[strat] = trainer
    np.testing.assert_allclose(losses["ddp"], losses["zero1"],
                               rtol=1e-6, atol=1e-7)

    # Structural: params replicated, at least one moment leaf sharded.
    z = trainers["zero1"]
    p_shardings = {
        str(leaf.sharding.spec)
        for leaf in jax.tree.leaves(z.state["params"])}
    assert p_shardings == {"PartitionSpec()"}
    m_specs = [leaf.sharding.spec
               for leaf in jax.tree.leaves(z.state["opt_state"])
               if hasattr(leaf, "sharding")]
    assert any(spec != () and any(ax is not None for ax in spec)
               for spec in m_specs), m_specs

    # PHYSICAL layout check (VERDICT r3 item 8): the arrays in z.state
    # came out of the COMPILED train step, so their shardings are the
    # executable's actual output layouts — not the trainer's request.
    # If XLA had silently degraded ZeRO-1 to replicated moments, each
    # device would hold the full array; sharded 8-way it holds 1/8.
    def device_frac(leaf):
        return leaf.addressable_shards[0].data.nbytes / leaf.nbytes

    opt_leaves = [x for x in jax.tree.leaves(z.state["opt_state"])
                  if hasattr(x, "addressable_shards") and x.ndim >= 2]
    assert opt_leaves, "no array moment leaves found"
    sharded_ids = {id(x) for x in opt_leaves
                   if device_frac(x) <= 1 / 8 + 1e-9}
    # Every >=2-D moment (mu and nu for each matmul weight) must be
    # physically 8-way sharded at min_shard_elems=1.
    assert len(sharded_ids) == len(opt_leaves), [
        (x.shape, str(x.sharding.spec)) for x in opt_leaves
        if id(x) not in sharded_ids]
    # And the aggregate opt-state HBM per device is ~1/8 of replicated
    # (scalars/count stay replicated; they are noise at this size).
    total = sum(x.nbytes for x in opt_leaves)
    per_dev = sum(x.addressable_shards[0].data.nbytes
                  for x in opt_leaves)
    assert per_dev <= total / 8 * 1.05
    # Params, by contrast, are physically replicated (DDP layout).
    p_leaf = jax.tree.leaves(z.state["params"])[0]
    assert device_frac(p_leaf) == 1.0


def test_fsdp_gather_for_compute_preserves_trajectory(cpu8):
    """The gather-for-compute binding (replicate weights forward,
    param-spec cotangents backward via the asymmetric custom VJP)
    changes only communication layout, never numerics: a short FSDP
    training trajectory must be identical with the binding on and
    off."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for gather in (True, False):
        rt = fake_cpu_runtime(8, fsdp=8)
        cfg = Config()
        cfg.train.batch_size = 1
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.optimizer = "adamw"
        cfg.train.learning_rate = 0.01
        cfg.train.parallel_strategy = "fsdp"
        cfg.train.min_shard_elems = 1
        cfg.train.fsdp_gather_for_compute = gather
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl="naive"))
        ds = SyntheticLMDataset(size=16, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=1, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        assert (model._compute_replicate is not None) == gather
        if gather:
            assert "attn/wq" in model._compute_bwd_specs
            assert "head" in model._compute_bwd_specs
        run = []
        for batch in loader.epoch(0):
            run.append(float(trainer.train_step(batch)["loss"]))
        losses[gather] = run
    import numpy as np
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-6, atol=1e-7)
