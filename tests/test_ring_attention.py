"""Ring attention correctness vs full attention on the 8-device CPU mesh
(the multi-chip sequence-parallel path, SURVEY.md §4.1 fixture)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.ops.attention import _naive_attention
from distributed_training_tpu.parallel.ring_attention import (
    ring_attention_global,
)
from distributed_training_tpu.runtime import fake_cpu_runtime

# This container's pinned jax runs the Pallas kernels in interpret
# mode and the ring/pipeline numerics at minutes per test — far over
# the tier-1 wall-clock budget (the whole file was broken-at-import
# at seed, so the fast gate never paid for it). The fast gate still
# COMPILES these paths every run (the analysis SPMD audit target
# lowers ring attention under the full sharded train step; the
# test_benchmarks contract tests compile the strategy matrix); the
# kernel/numerics suites here run via `pytest -m slow`.
pytestmark = pytest.mark.slow


def rand_qkv(B=2, S=64, H=4, D=16, Hkv=None, seed=0):
    Hkv = Hkv or H
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, S, H, D)),
            jax.random.normal(ks[1], (B, S, Hkv, D)),
            jax.random.normal(ks[2], (B, S, Hkv, D)))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_full(causal, sp):
    rt = fake_cpu_runtime(8, sp=sp)
    q, k, v = rand_qkv()
    out = ring_attention_global(q, k, v, rt.mesh, causal=causal)
    ref = _naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa():
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(H=4, Hkv=2)
    out = ring_attention_global(q, k, v, rt.mesh, causal=True)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_data_parallel_axes():
    """sp composes with dp: mesh (dp=2, sp=4), batch sharded over dp."""
    rt = fake_cpu_runtime(8, sp=4)  # dp=2 fills the rest
    assert rt.spec.dp == 2
    q, k, v = rand_qkv(B=4)
    out = ring_attention_global(q, k, v, rt.mesh, causal=True)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_full():
    """MHA gradients through the reverse-ring custom VJP must match
    full-attention autodiff (GQA variant covered separately below)."""
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(S=32, H=2, D=8)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention_global(q, k, v, rt.mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch")


def test_ring_sp1_degenerates_to_full():
    rt = fake_cpu_runtime(8)  # sp=1
    q, k, v = rand_qkv()
    out = ring_attention_global(q, k, v, rt.mesh, causal=True)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sp_training_end_to_end_matches_dp():
    """Full train steps with ring attention on a (dp=2, sp=4) mesh must
    produce the same loss trajectory as naive attention on a plain dp=2
    mesh: both see 2 data shards, so batches are identical and only the
    attention/layout implementation differs."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, impl in (("dp", 2, {}, "naive"),
                                  ("sp", 8, {"sp": 4}, "ring")):
        rt = fake_cpu_runtime(ndev, **axes)
        assert rt.data_shard_count == 2
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=impl))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64, seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["sp"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_gradients_gqa_reverse_ring(causal):
    """The reverse-ring custom VJP (KV re-rotated, dk/dv traveling with
    their block) must match full-attention gradients, including grouped
    KV heads where dk/dv reduce over the query group."""
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(S=32, H=4, D=8, Hkv=2, seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention_global(q, k, v, rt.mesh, causal=causal) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, causal=causal) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_ring_gradients_bf16_inputs():
    """bf16 q/k/v: grads come back bf16 and track the fp32 reference."""
    rt = fake_cpu_runtime(8, sp=2)
    q, k, v = rand_qkv(S=32, H=2, D=8, seed=5)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def loss_ring(q, k, v):
        out = ring_attention_global(q, k, v, rt.mesh, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gb = jax.grad(loss_ring, argnums=(0, 1, 2))(qb, kb, vb)
    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            _naive_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gb, gf, "qkv"):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b),
            rtol=0.1, atol=0.15, err_msg=f"d{name} drifted")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_blocks_match_naive_blocks(causal):
    """block_impl='flash' routes each ring block through the Pallas
    kernels (interpret mode on CPU) — values AND reverse-ring grads
    must match the einsum block path."""
    from distributed_training_tpu.parallel.ring_attention import (
        make_ring_attention,
    )
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(B=1, S=32, H=2, D=8, seed=7)

    def loss(impl):
        fn = make_ring_attention(rt.mesh, causal=causal,
                                 batch_axes=(), block_impl=impl)
        return lambda q, k, v: jnp.sum(jax.jit(fn)(q, k, v) ** 2)

    of = loss("flash")(q, k, v)
    on = loss("naive")(q, k, v)
    np.testing.assert_allclose(float(of), float(on), rtol=1e-5)

    gf = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss("naive"), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=f"d{name} flash-block mismatch")


def test_ring_flash_blocks_gqa():
    from distributed_training_tpu.parallel.ring_attention import (
        make_ring_attention,
    )
    rt = fake_cpu_runtime(8, sp=2)
    q, k, v = rand_qkv(B=1, S=32, H=4, D=8, Hkv=2, seed=8)
    fn = make_ring_attention(rt.mesh, causal=True, batch_axes=(),
                             block_impl="flash")
    out = jax.jit(fn)(q, k, v)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda q: jnp.sum(jax.jit(fn)(q, k, v) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        _naive_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=1e-4, atol=1e-4)


def test_ring_forced_flash_adapts_tiles_to_odd_shards():
    """block_impl='flash' on a shard length with no 256-tile fit
    (S_local=384) used to raise; the seq-aware kernel defaults
    (ops/flash_attention.default_blocks) now pick a dividing tile
    (128) so forced flash runs — and matches the naive reference.
    Explicit non-dividing overrides still raise (covered by
    test_ring_tile_overrides_validated)."""
    import numpy as np
    from distributed_training_tpu.ops import flash_attention as fa
    from distributed_training_tpu.parallel.ring_attention import (
        make_ring_attention,
    )
    assert fa.default_blocks(384, 384, 8) == (128, 128)
    # Shards with no dividing power-of-two tile >= 128 fall through to
    # a single whole-shard block rather than a partial grid.
    assert fa.default_blocks(192, 192, 8) == (192, 192)
    rt = fake_cpu_runtime(8, sp=2)
    # S_global=768 -> S_local=384: > 256 but not a multiple of 256
    q, k, v = rand_qkv(B=1, S=768, H=2, D=8, seed=9)
    fn = make_ring_attention(rt.mesh, causal=True, batch_axes=(),
                             block_impl="flash")
    out = jax.jit(fn)(q, k, v)
    ref = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_tile_overrides_validated(cpu8):
    """flash_block_q/k thread into the ring (one sweep knob for every
    attention layout); overrides that don't divide the local shard
    raise instead of being silently ignored."""
    import jax
    from distributed_training_tpu.parallel.ring_attention import (
        make_ring_attention,
    )
    from distributed_training_tpu.runtime import fake_cpu_runtime
    rt = fake_cpu_runtime(8, sp=4)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 4, 16)) for kk in ks)
    # S_local = 16; 12 does not divide it -> loud failure.
    bad = make_ring_attention(rt.mesh, block_q=12)
    with pytest.raises(ValueError, match="tile overrides"):
        jax.jit(bad)(q, k, v)
    # 16 divides -> fine (naive fallback on CPU, same validation path).
    ok = make_ring_attention(rt.mesh, block_q=16, block_k=16)
    out = jax.jit(ok)(q, k, v)
    assert out.shape == q.shape


@pytest.mark.parametrize("window", [1, 5, 16, 20, 40, 64])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_windowed_matches_full(window, sp):
    """Sliding-window ring attention == single-device windowed
    attention, in GLOBAL positions across shard boundaries. Windows
    chosen to hit every geometry: self-only (1), intra-block (5),
    exactly one block (16 at sp=4), one-block spill (20), multi-block
    (40), full-sequence (64 == S, the degenerate all-visible case)."""
    rt = fake_cpu_runtime(8, sp=sp)
    q, k, v = rand_qkv()  # S=64
    out = ring_attention_global(q, k, v, rt.mesh, causal=True,
                                window=window)
    ref = _naive_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_windowed_gqa():
    """The capability this closes (VERDICT r3 weak item 7): a GQA
    model with few KV heads AND a window now has a sequence-parallel
    option — Hkv=2 rules out Ulysses at tp*sp=8 (2 % 8 != 0)."""
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(H=4, Hkv=2)
    out = ring_attention_global(q, k, v, rt.mesh, causal=True,
                                window=20)
    ref = _naive_attention(q, k, v, causal=True, window=20)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [5, 20])
def test_ring_windowed_gradients(window):
    """Reverse-ring VJP under the window: grads must match windowed
    full-attention autodiff, including zero dk/dv for out-of-window
    (skipped) blocks."""
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(S=32, H=4, D=8, Hkv=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_global(
            q, k, v, rt.mesh, causal=True, window=window) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_naive_attention(
            q, k, v, causal=True, window=window) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch")


def test_ring_windowed_requires_causal():
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv()
    with pytest.raises(ValueError, match="requires causal"):
        ring_attention_global(q, k, v, rt.mesh, causal=False,
                              window=8)


def test_ring_windowed_sp1_degenerate():
    rt = fake_cpu_runtime(8)  # sp=1
    q, k, v = rand_qkv()
    out = ring_attention_global(q, k, v, rt.mesh, causal=True,
                                window=20)
    ref = _naive_attention(q, k, v, causal=True, window=20)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_windowed_rejects_forced_flash_and_bad_tiles():
    """window > 0 runs einsum blocks; forcing the flash kernel or
    passing non-dividing tile overrides must raise, not silently
    demote (the raise-don't-ignore sweep contract)."""
    from distributed_training_tpu.parallel.ring_attention import (
        make_ring_attention,
    )
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv()
    forced = make_ring_attention(rt.mesh, block_impl="flash", window=8)
    with pytest.raises(ValueError, match="unsupported with window"):
        jax.jit(forced)(q, k, v)
    bad = make_ring_attention(rt.mesh, block_q=12, window=8)
    with pytest.raises(ValueError, match="tile overrides"):
        jax.jit(bad)(q, k, v)


@pytest.mark.parametrize("window", [5, 12, 20])
def test_ring_windowed_diagonal_flash_matches_naive(monkeypatch,
                                                    window):
    """Under a window the diagonal block routes through the Pallas
    kernel (aligned band mask, interpret mode on CPU) while offset
    blocks stay einsum — values and reverse-ring grads must match the
    all-einsum path. Forced on by stubbing the tile gate (CPU would
    otherwise decline flash)."""
    from distributed_training_tpu.parallel import ring_attention as ra

    monkeypatch.setattr(ra, "_flash_block_ok",
                        lambda *a, **k: True)
    rt = fake_cpu_runtime(8, sp=4)
    q, k, v = rand_qkv(B=1, S=32, H=2, D=8, seed=11)

    def loss(q, k, v):
        fn = ra.make_ring_attention(rt.mesh, causal=True,
                                    batch_axes=(), window=window)
        return jnp.sum(jax.jit(fn)(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_naive_attention(
            q, k, v, causal=True, window=window) ** 2)

    np.testing.assert_allclose(float(loss(q, k, v)),
                               float(loss_ref(q, k, v)), rtol=1e-5)
    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch")
