"""Config layer tests: composition, overrides, typed resolution.

Covers the behaviors the reference delegated to Hydra
(reference: conf/config.yaml:1-14, src/distributed_trainer.py:243-258).
"""

import os

import pytest

from distributed_training_tpu.config import (
    Config, ConfigError, compose, config_from_dict, load_config,
    override_config, save_resolved,
)

CONF = os.path.join(os.path.dirname(os.path.dirname(__file__)), "conf")


def test_defaults_match_reference():
    cfg = load_config(CONF)
    # Parity targets: reference conf/train/default.yaml + conf/model/default.yaml
    assert cfg.train.batch_size == 32
    assert cfg.train.total_epochs == 10
    assert cfg.train.save_every == 2
    assert cfg.train.dataset_size == 2048
    assert cfg.train.learning_rate == pytest.approx(1e-3)
    assert cfg.train.parallel_strategy == "ddp"
    assert cfg.model.name == "mlp"
    assert cfg.model.kwargs["input_size"] == 20
    assert cfg.model.kwargs["output_size"] == 1


def test_snapshot_path_is_anchored():
    # Fixes reference bug B2 (relative snapshot path + chdir kills resume).
    cfg = load_config(CONF)
    assert os.path.isabs(cfg.train.snapshot_path)


def test_leaf_overrides():
    cfg = load_config(CONF, overrides=[
        "train.batch_size=64",
        "train.learning_rate=0.01",
        "mesh.fsdp=4",
        "mesh.dp=2",
    ])
    assert cfg.train.batch_size == 64
    assert cfg.train.learning_rate == pytest.approx(0.01)
    assert cfg.mesh.fsdp == 4
    assert cfg.mesh.dp == 2


def test_unknown_leaf_rejected_without_plus():
    with pytest.raises(ConfigError):
        load_config(CONF, overrides=["train.nope=1"])


def test_plus_adds_new_key():
    tree = compose(CONF, overrides=["+model.n_layer=12"])
    assert tree["model"]["n_layer"] == 12
    cfg = config_from_dict(tree)
    assert cfg.model.kwargs["n_layer"] == 12


def test_group_swap(tmp_path):
    (tmp_path / "model").mkdir()
    (tmp_path / "train").mkdir()
    (tmp_path / "mesh").mkdir()
    (tmp_path / "config.yaml").write_text(
        "defaults:\n  - model: default\n  - train: default\n")
    (tmp_path / "model" / "default.yaml").write_text("name: mlp\n")
    (tmp_path / "model" / "big.yaml").write_text("name: transformer\n")
    (tmp_path / "train" / "default.yaml").write_text("batch_size: 8\n")
    cfg = load_config(str(tmp_path), overrides=["model=big"])
    assert cfg.model.name == "transformer"


def test_roundtrip_save(tmp_path):
    cfg = load_config(CONF)
    path = str(tmp_path / "resolved.yaml")
    save_resolved(cfg, path)
    assert os.path.exists(path)


def test_override_config_helper():
    cfg = Config()
    cfg2 = override_config(cfg, train={"batch_size": 4})
    assert cfg2.train.batch_size == 4
    assert cfg.train.batch_size == 32  # original untouched
    with pytest.raises(ConfigError):
        override_config(cfg, train={"bogus": 1})


def test_override_scalar_intermediate_rejected():
    # Regression: 'train.batch_size.typo=1' must not clobber batch_size
    # with a dict.
    with pytest.raises(ConfigError):
        load_config(CONF, overrides=["train.batch_size.typo=1"])
    with pytest.raises(ConfigError):
        load_config(CONF, overrides=["+train.batch_size.typo=1"])


def test_scientific_notation_override_coerces():
    """PyYAML parses dot-less exponents ('3e-3') as STRINGS; the schema
    boundary must coerce them into float fields (this silently broke
    any CLI run setting train.learning_rate=3e-3)."""
    from distributed_training_tpu.config import (ConfigError,
                                                 config_from_dict)
    cfg = config_from_dict({"train": {"learning_rate": "3e-3",
                                      "batch_size": "16",
                                      "nan_guard": "true"}})
    assert cfg.train.learning_rate == pytest.approx(3e-3)
    assert cfg.train.batch_size == 16
    assert cfg.train.nan_guard is True
    with pytest.raises(ConfigError, match="learning_rate"):
        config_from_dict({"train": {"learning_rate": "fast"}})


def test_int_field_rejects_fractional_float():
    from distributed_training_tpu.config import (ConfigError,
                                                 config_from_dict)
    cfg = config_from_dict({"train": {"batch_size": 32.0}})
    assert cfg.train.batch_size == 32 and \
        isinstance(cfg.train.batch_size, int)
    with pytest.raises(ConfigError, match="batch_size"):
        config_from_dict({"train": {"batch_size": 2.5}})


def test_longcontext_preset_composes_and_trains():
    """model=longcontext_7b + train=longcontext: the first-class
    long-context surface (windowed GQA ring at 32k). Composition is
    checked at full scale; the train step runs at a shrunken geometry
    on the sp mesh (same code path, CPU-sized)."""
    cfg = load_config(CONF, overrides=["model=longcontext_7b",
                                       "train=longcontext"])
    assert cfg.model.name == "transformer_7b"
    kw = cfg.model.kwargs
    assert kw["attention_impl"] == "ring"
    assert kw["attention_window"] == 4096
    assert kw["max_seq_len"] == 32768
    assert cfg.train.dataset_kwargs["seq_len"] == 32768
    assert cfg.train.parallel_strategy == "fsdp"

    # Shrunken end-to-end: same composition, toy geometry.
    import numpy as np

    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.runtime import fake_cpu_runtime
    from distributed_training_tpu.train.trainer import Trainer

    cfg = load_config(CONF, overrides=[
        "model=longcontext_7b", "train=longcontext",
        "train.dtype=float32", "train.batch_size=2",
        "train.log_every=0", "train.min_shard_elems=1",
        "model.kwargs.max_seq_len=64",
        "model.kwargs.attention_window=24",
        "model.kwargs.d_model=64", "model.kwargs.n_layers=2",
        "model.kwargs.n_heads=4", "model.kwargs.n_kv_heads=2",
        "model.kwargs.vocab_size=128",
        "train.dataset_kwargs.seq_len=64",
        "train.dataset_kwargs.vocab_size=128",
        "train.dataset_size=16",
    ])
    rt = fake_cpu_runtime(8, sp=2, fsdp=2)
    model = build_model(cfg.model.name, dtype=cfg.train.dtype,
                        **cfg.model.kwargs)
    ds = SyntheticLMDataset(
        size=cfg.train.dataset_size,
        seq_len=cfg.train.dataset_kwargs["seq_len"],
        vocab_size=cfg.train.dataset_kwargs["vocab_size"], seed=0)
    loader = ShardedDataLoader(ds, rt,
                               batch_size=cfg.train.batch_size,
                               shuffle=False)
    trainer = Trainer(cfg, rt, model, loader)
    loss = float(trainer.train_step(
        next(iter(loader.epoch(0))))["loss"])
    assert np.isfinite(loss)
