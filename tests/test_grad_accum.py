"""Gradient accumulation and remat policies.

Both are the memory levers for the BASELINE.json 1B/7B FSDP configs:
accumulation shrinks per-microbatch activations at fixed effective
batch; remat drops block internals and recomputes them in backward.
Neither may change the math — that is what these tests pin.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models.mlp import MLP
from distributed_training_tpu.models.transformer import (Transformer,
                                                         TransformerConfig)
from distributed_training_tpu.train.trainer import Trainer


def run_losses(rt, accum, steps=6):
    cfg = Config()
    cfg.train.parallel_strategy = "ddp"
    cfg.train.batch_size = 8  # per shard; global 64
    cfg.train.total_epochs = 1
    cfg.train.learning_rate = 0.05
    cfg.train.log_every = 0
    cfg.train.shuffle = False
    cfg.train.grad_accum_steps = accum
    ds = SyntheticRegressionDataset(size=512, in_dim=20, out_dim=1,
                                    seed=0, kind="linear")
    loader = ShardedDataLoader(ds, rt, batch_size=8, shuffle=False)
    model = MLP(input_size=20, output_size=1, loss_name="mse")
    trainer = Trainer(cfg, rt, model, loader)
    losses = []
    for i, batch in enumerate(loader.epoch(0)):
        if i >= steps:
            break
        losses.append(float(trainer.train_step(batch)["loss"]))
    return losses


def test_grad_accum_matches_single_pass(cpu8):
    """MSE mean loss decomposes over equal microbatches, so mean-of-
    microbatch-grads == full-batch grad: accum=4 must reproduce accum=1
    step-for-step (same data order, SGD)."""
    base = run_losses(cpu8, accum=1)
    acc = run_losses(cpu8, accum=4)
    np.testing.assert_allclose(acc, base, rtol=2e-5, atol=1e-6)


def test_grad_accum_uneven_split_fails_loudly(cpu8):
    # per-shard batch is 8; 7 doesn't divide it → Trainer rejects it
    # up front (a silent GSPMD reshard would otherwise eat the perf).
    with pytest.raises(ValueError, match="grad_accum_steps"):
        run_losses(cpu8, accum=7, steps=1)


def tiny_tf(remat, policy="selective"):
    return Transformer(TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4,
        max_seq_len=16, dtype="float32", param_dtype="float32",
        remat=remat, remat_policy=policy, attention_impl="naive"))


def test_remat_policies_preserve_loss_and_grads():
    """full and selective remat change memory/recompute schedules only —
    loss and gradients must match the non-remat forward."""
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
    batch = {"tokens": tokens}
    ref_model = tiny_tf(remat=False)
    params = ref_model.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    def loss_of(model):
        def f(p):
            loss, _ = model.loss(p, batch, rng)
            return loss
        return jax.jit(jax.value_and_grad(f))(params)

    ref_loss, ref_grads = loss_of(ref_model)
    for policy in ("full", "selective", "mlp"):
        loss, grads = loss_of(tiny_tf(remat=True, policy=policy))
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            grads, ref_grads)


def test_remat_unknown_policy_raises():
    with pytest.raises(ValueError, match="remat_policy"):
        model = tiny_tf(remat=True, policy="bogus")
        params = model.init(jax.random.PRNGKey(0))
        model.apply(params, jnp.zeros((1, 8), jnp.int32))


def test_remat_mlp_policy_covers_moe():
    """remat_policy='mlp' must (a) leave loss/grads exactly equal to
    the non-remat model and (b) actually SAVE FEWER residual bytes —
    the structural half catches the failure numerics cannot: a policy
    that silently saves everything (e.g. the aliasing-defeated
    save_anything_except_these_names this repo abandoned) is
    numerically identical but retains every F-wide expert hidden
    (the OOM class the policy exists to drop)."""
    def moe_tf(remat):
        return Transformer(TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", param_dtype="float32",
            moe_num_experts=4, moe_top_k=2, attention_impl="naive",
            remat=remat, remat_policy="mlp"))

    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 128, (2, 16)), jnp.int32)
    batch = {"tokens": tokens}
    ref_model = moe_tf(remat=False)
    params = ref_model.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    def loss_of(model):
        def f(p):
            loss, _ = model.loss(p, batch, rng)
            return loss
        return jax.jit(jax.value_and_grad(f))(params)

    ref_loss, ref_grads = loss_of(ref_model)
    loss, grads = loss_of(moe_tf(remat=True))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        grads, ref_grads)

    try:  # public in newer jax; private in the pinned version
        from jax.ad_checkpoint import saved_residuals
    except ImportError:
        from jax._src.ad_checkpoint import saved_residuals

    def residual_bytes(model):
        def f(p):
            loss, _ = model.loss(p, batch, rng)
            return loss
        return sum(
            int(np.prod(aval.shape)) * aval.dtype.itemsize
            for aval, _ in saved_residuals(f, params)
            if hasattr(aval, "shape") and aval.shape)

    saved_no_remat = residual_bytes(ref_model)
    saved_mlp = residual_bytes(moe_tf(remat=True))
    assert saved_mlp < saved_no_remat, (
        f"remat_policy='mlp' saved {saved_mlp} residual bytes vs "
        f"{saved_no_remat} without remat — the policy is a no-op "
        "(checkpoint_name tags missing from the MoE MLP?)")
