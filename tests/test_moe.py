"""Routed MoE dispatch: numerics vs the dense reference, FLOPs scaling
independent of expert count, capacity-drop semantics.

VERDICT round-2 item 3: dense dispatch computed every expert for every
token (O(E) FLOPs); the routed path must cost ~top_k experts per token
regardless of E.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.models import build_model
from distributed_training_tpu.models.transformer import (
    TransformerConfig, _moe_group_size, _moe_mlp_dense, _moe_mlp_routed,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                max_seq_len=16, dtype="float32", param_dtype="float32",
                moe_num_experts=4, moe_top_k=2)
    base.update(kw)
    return TransformerConfig(**base)


def _mlp_params(c, seed=0):
    rng = np.random.default_rng(seed)
    E, D, F = c.moe_num_experts, c.d_model, c.d_ff
    return {
        "router": jnp.asarray(rng.standard_normal((D, E)), jnp.float32),
        "wi": jnp.asarray(
            rng.standard_normal((E, D, F)) * 0.05, jnp.float32),
        "wo": jnp.asarray(
            rng.standard_normal((E, F, D)) * 0.05, jnp.float32),
    }


def test_group_size_pads_up():
    """Group size never collapses for poorly-composite sequence
    lengths; S pads up (groups are per-row sequence chunks)."""
    assert _moe_group_size(1024, 1024) == (1024, 1024)
    assert _moe_group_size(2048, 1024) == (1024, 2048)
    assert _moe_group_size(992, 1024) == (992, 992)
    assert _moe_group_size(992, 500) == (500, 1000)
    assert _moe_group_size(7, 4) == (4, 8)
    assert _moe_group_size(2 * 1031, 1024) == (1024, 3072)


def test_routed_ragged_tokens_match_dense():
    """T not divisible by the group cap: pad rows must claim no
    capacity and contribute nothing (output still matches dense)."""
    c = _cfg(moe_top_k=2, moe_capacity_factor=4.0, moe_group_size=5)
    mlp = _mlp_params(c)
    h = jnp.asarray(
        np.random.default_rng(4).standard_normal((1, 13, 32)),
        jnp.float32)  # T=13, g=5 -> pads to 15
    out_r, aux_r = _moe_mlp_routed(h, mlp, c)
    out_d, aux_d = _moe_mlp_dense(h, mlp, c)
    np.testing.assert_allclose(out_r, out_d, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(aux_r, aux_d, rtol=1e-5, atol=0)


@pytest.mark.parametrize("top_k", [1, 2])
def test_routed_matches_dense_at_ample_capacity(top_k):
    """With capacity big enough that nothing drops, routed == dense
    (values and grads): same experts, same combine weights."""
    c = _cfg(moe_top_k=top_k,
             moe_capacity_factor=4.0,  # C = k*g: nothing can drop
             moe_group_size=32)
    mlp = _mlp_params(c)
    h = jnp.asarray(np.random.default_rng(1).standard_normal((2, 8, 32)),
                    jnp.float32)

    out_r, aux_r = _moe_mlp_routed(h, mlp, c)
    out_d, aux_d = _moe_mlp_dense(h, mlp, c)
    np.testing.assert_allclose(out_r, out_d, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(aux_r, aux_d, rtol=1e-6, atol=0)

    gr = jax.grad(lambda m: jnp.sum(_moe_mlp_routed(h, m, c)[0]))(mlp)
    gd = jax.grad(lambda m: jnp.sum(_moe_mlp_dense(h, m, c)[0]))(mlp)
    for key in ("router", "wi", "wo"):
        np.testing.assert_allclose(gr[key], gd[key], rtol=1e-4,
                                   atol=1e-5)


def test_capacity_drops_tokens():
    """With capacity 1 per expert, overflowing tokens contribute
    nothing (out rows can be zero) and nothing NaNs."""
    c = _cfg(moe_top_k=1, moe_capacity_factor=1e-6, moe_group_size=16)
    mlp = _mlp_params(c)
    h = jnp.asarray(np.random.default_rng(2).standard_normal((1, 16, 32)),
                    jnp.float32)
    out, aux = _moe_mlp_routed(h, mlp, c)
    assert np.all(np.isfinite(np.asarray(out)))
    assert np.isfinite(float(aux))
    # capacity C=1 per expert, 4 experts, 16 tokens -> at most 4 rows
    # received any expert output.
    nonzero_rows = np.sum(np.any(np.asarray(out[0]) != 0.0, axis=-1))
    assert nonzero_rows <= 4


def _model_flops(E: int, moe_impl: str) -> float:
    model = build_model("transformer", vocab_size=128, d_model=64,
                        n_layers=2, n_heads=4, max_seq_len=64,
                        dtype="float32", param_dtype="float32",
                        moe_num_experts=E, moe_top_k=2,
                        moe_impl=moe_impl, moe_group_size=256)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((4, 64), jnp.int32)
    lowered = jax.jit(
        lambda p, t: model.apply(p, t)[0]).lower(params, tokens)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0]
    return float(cost["flops"])


def test_routed_flops_independent_of_expert_count():
    """Doubling E at fixed top_k must not ~double routed FLOPs (it does
    for dense). Compiled-cost assertion, per VERDICT item 3."""
    r4, r16 = _model_flops(4, "routed"), _model_flops(16, "routed")
    d4, d16 = _model_flops(4, "dense"), _model_flops(16, "dense")
    assert d16 / d4 > 2.0, f"dense should scale with E: {d4} -> {d16}"
    assert r16 / r4 < 1.5, (
        f"routed FLOPs should be ~independent of E: {r4} -> {r16}")


def test_moe_model_trains_routed(cpu8):
    """End-to-end: routed-MoE transformer takes a finite training step
    under the trainer on the 8-device mesh (EP layout)."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.train.trainer import Trainer

    cfg = Config()
    cfg.train.parallel_strategy = "fsdp"
    cfg.train.batch_size = 2
    cfg.train.log_every = 0
    cfg.train.min_shard_elems = 1
    cfg.train.dtype = "float32"
    model = build_model("transformer", vocab_size=128, d_model=32,
                        n_layers=2, n_heads=4, max_seq_len=16,
                        dtype="float32", moe_num_experts=4,
                        moe_group_size=64)
    ds = SyntheticLMDataset(size=32, seq_len=16, vocab_size=128, seed=0)
    loader = ShardedDataLoader(ds, cpu8, batch_size=2, shuffle=False)
    trainer = Trainer(cfg, cpu8, model, loader)
    batch = next(iter(loader.epoch(0)))
    m1 = trainer.train_step(batch)
    m2 = trainer.train_step(batch)
    assert np.isfinite(float(m1["loss"]))
    assert float(m2["loss"]) < float(m1["loss"]) + 1.0


def test_moe_composes_with_ulysses(cpu8):
    """Routed MoE under Ulysses sequence parallelism: attention
    re-shards (seq <-> heads) around an MLP whose token routing is
    oblivious to the sp layout — losses must match plain dp."""
    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from distributed_training_tpu.runtime import fake_cpu_runtime
    from distributed_training_tpu.train.trainer import Trainer

    losses = {}
    for tag, ndev, axes, impl in (("dp", 2, {}, "naive"),
                                  ("sp", 8, {"sp": 4}, "ulysses")):
        rt = fake_cpu_runtime(ndev, **axes)
        cfg = Config()
        cfg.train.batch_size = 2
        cfg.train.total_epochs = 1
        cfg.train.log_every = 0
        cfg.train.learning_rate = 0.01
        model = Transformer(TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4,
            max_seq_len=16, dtype="float32", attention_impl=impl,
            moe_num_experts=4, moe_top_k=2))
        ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=64,
                                seed=0)
        loader = ShardedDataLoader(ds, rt, batch_size=2, shuffle=False)
        trainer = Trainer(cfg, rt, model, loader)
        losses[tag] = [float(trainer.train_step(b)["loss"])
                       for b in loader.epoch(0)]
    np.testing.assert_allclose(losses["dp"], losses["sp"],
                               rtol=1e-5, atol=1e-6)


def test_topk_by_argmax_matches_lax_topk_fwd_and_bwd():
    """Routing selects via _topk_by_argmax (the SPMD partitioner
    cannot partition lax.top_k's TopK custom-call and all-gathered the
    routing probs across shards — BENCH_r04 contract remainder, fixed
    r5). Selection, ordering AND gradient must match lax.top_k exactly
    — including tied probs (a freshly-initialized router ties every
    expert; jnp.max's VJP would split the cotangent across ties,
    leaking gradient onto unselected experts)."""
    from distributed_training_tpu.models.transformer import (
        _topk_by_argmax,
    )

    cases = [
        jnp.asarray([0.5, 0.5, 0.1, 0.5]),          # ties
        jnp.asarray([0.25, 0.25, 0.25, 0.25]),      # all tied (init)
        jax.random.uniform(jax.random.PRNGKey(0), (3, 5, 7)),
    ]
    for x in cases:
        for k in (1, 2):
            v_ref, i_ref = jax.lax.top_k(x, k)
            v, i = _topk_by_argmax(x, k)
            np.testing.assert_array_equal(np.asarray(i_ref),
                                          np.asarray(i))
            np.testing.assert_allclose(np.asarray(v_ref),
                                       np.asarray(v))
            g_ref = jax.grad(
                lambda p: jnp.sum(jax.lax.top_k(p, k)[0] ** 2))(x)
            g = jax.grad(
                lambda p: jnp.sum(_topk_by_argmax(p, k)[0] ** 2))(x)
            np.testing.assert_allclose(np.asarray(g_ref),
                                       np.asarray(g))
