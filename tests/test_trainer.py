"""End-to-end trainer tests on the 8-device CPU mesh.

This is SURVEY.md §7's "minimum end-to-end slice": config-driven MLP on
the synthetic dataset, DP and FSDP layouts, convergence on the learnable
task, replica consistency, and loss parity across strategies.
"""


import jax
import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models.mlp import MLP
from distributed_training_tpu.runtime import fake_cpu_runtime
from distributed_training_tpu.train.trainer import Trainer


def make_trainer(rt, strategy="ddp", loss="mse", epochs=2, dataset=None,
                 **train_over):
    cfg = Config()
    cfg.train.parallel_strategy = strategy
    cfg.train.total_epochs = epochs
    cfg.train.batch_size = 4
    cfg.train.dataset_size = 128
    cfg.train.log_every = 0
    for k, v in train_over.items():
        setattr(cfg.train, k, v)
    ds = dataset or SyntheticRegressionDataset(
        size=cfg.train.dataset_size, in_dim=20, out_dim=1, seed=0,
        kind="linear")
    loader = ShardedDataLoader(ds, rt, batch_size=cfg.train.batch_size,
                               shuffle=cfg.train.shuffle,
                               seed=cfg.train.seed)
    model = MLP(input_size=20, output_size=1, loss_name=loss)
    return Trainer(cfg, rt, model, loader), cfg


def test_mlp_converges_dp(cpu8):
    trainer, _ = make_trainer(cpu8, "ddp", epochs=5,
                              learning_rate=0.05)
    first = trainer._run_epoch(0)["mean_loss"]
    summary = trainer.train()
    assert summary["mean_loss"] < first * 0.5, (
        f"no convergence: first={first}, last={summary['mean_loss']}")


def test_dp_and_fsdp_agree(cpu8):
    """DDP and FSDP are the same math in different layouts — identical
    data + init must give near-identical loss trajectories (the
    loss-curve-parity requirement, BASELINE.json north star)."""
    rt_fsdp = fake_cpu_runtime(8, fsdp=8)
    losses = {}
    for tag, rt, strat in (("ddp", cpu8, "ddp"), ("fsdp", rt_fsdp, "fsdp")):
        # min_shard_elems=1 forces real sharding of the tiny MLP's params
        # under fsdp (the (20,1) kernel won't split 8 ways, but bias and
        # any divisible dims will; layout differs from ddp either way).
        trainer, _ = make_trainer(rt, strat, epochs=2, learning_rate=0.05,
                                  min_shard_elems=1)
        summary = trainer.train()
        losses[tag] = summary["mean_loss"]
    assert losses["ddp"] == pytest.approx(losses["fsdp"], rel=1e-4)


def test_prob_xent_parity_is_gradient_free(cpu8):
    """Reference B5 preserved: the degenerate single-logit prob-xent loss
    trains nothing — loss identically 0, params unchanged."""
    ds = SyntheticRegressionDataset(size=64, seed=0)  # uniform parity data
    trainer, _ = make_trainer(cpu8, "ddp", loss="prob_xent", epochs=1,
                              dataset=ds, dataset_size=64)
    params_before = jax.tree.map(np.asarray, trainer.state["params"])
    summary = trainer.train()
    assert summary["mean_loss"] == pytest.approx(0.0, abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        trainer.state["params"], params_before)


def test_step_counter_and_state_sharded(cpu8):
    trainer, _ = make_trainer(cpu8, "ddp", epochs=1)
    trainer.train()
    # 128 samples / 8 shards / batch 4 = 4 steps/epoch
    assert int(trainer.state["step"]) == 4
    assert trainer.epochs_run == 1


def test_fsdp_params_actually_sharded():
    rt = fake_cpu_runtime(8, fsdp=8)
    trainer, _ = make_trainer(rt, "fsdp", epochs=1,
                              dataset=SyntheticRegressionDataset(
                                  size=128, in_dim=64, out_dim=8, seed=0,
                                  kind="linear"))
    # With min_shard_elems default the tiny MLP replicates; rebuild a
    # trainer with a bigger layer via hidden sizes to check sharding.
    model = MLP(input_size=64, output_size=8, hidden_sizes=[512])
    from distributed_training_tpu.parallel import get_strategy
    strat = get_strategy("fsdp", rt.spec, min_shard_elems=1)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    specs = strat.specs_for_tree(shapes, model.logical_axes())
    # embedding-dim rule routes w to fsdp
    assert any("fsdp" in str(s) for s in jax.tree.leaves(
        specs, is_leaf=lambda x: True))


def test_nan_guard_skips_bad_step(cpu8):
    ds = SyntheticRegressionDataset(size=64, in_dim=20, out_dim=1,
                                    seed=0, kind="linear")
    bad = dict(ds.columns)
    bad["x"] = bad["x"].copy()
    bad["x"][:] = np.nan
    from distributed_training_tpu.data.datasets import ArrayDataset
    nan_ds = ArrayDataset(**bad)
    trainer, _ = make_trainer(cpu8, "ddp", epochs=1, dataset=nan_ds,
                              dataset_size=64, nan_guard=True)
    params_before = jax.tree.map(np.asarray, trainer.state["params"])
    trainer.train()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        trainer.state["params"], params_before)


def test_adamw_cosine_warmup(cpu8):
    trainer, _ = make_trainer(cpu8, "ddp", epochs=2, optimizer="adamw",
                              lr_schedule="cosine", warmup_steps=2,
                              grad_clip_norm=1.0, learning_rate=0.01)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])


def test_evaluate(cpu8):
    trainer, _ = make_trainer(cpu8, "ddp", epochs=1)
    batches = list(trainer.loader.epoch(0))
    val = trainer.evaluate(batches)
    assert np.isfinite(val)


def test_save_every_zero_disables_checkpointing(cpu8, tmp_path):
    """save_every=0 means 'never save' — regression: it used to crash
    with ZeroDivisionError when a checkpointer was attached (the CLI
    always attaches one)."""
    from distributed_training_tpu.checkpoint import Checkpointer
    from distributed_training_tpu.data import SyntheticRegressionDataset

    cfg = Config()
    cfg.train.total_epochs = 2
    cfg.train.save_every = 0
    cfg.train.batch_size = 4
    cfg.train.log_every = 0
    cfg.train.snapshot_path = str(tmp_path / "ckpt")
    ds = SyntheticRegressionDataset(size=32, seed=0, kind="linear")
    loader = ShardedDataLoader(ds, cpu8, batch_size=4, shuffle=False)
    model = MLP(input_size=20, output_size=1)
    ckpt = Checkpointer(cfg.train.snapshot_path, async_save=False)
    trainer = Trainer(cfg, cpu8, model, loader, ckpt)
    trainer.train()
    assert ckpt.latest_step() is None  # nothing saved
    ckpt.close()


def test_metrics_jsonl_stream(cpu8, tmp_path):
    """metrics_jsonl appends one JSON line per recorded entry (loss
    rows and unthrottled val_loss rows)."""
    import json

    from distributed_training_tpu.data import SyntheticRegressionDataset
    from distributed_training_tpu.data.datasets import train_eval_split

    cfg = Config()
    cfg.train.total_epochs = 2
    cfg.train.batch_size = 4
    cfg.train.log_every = 1
    cfg.train.eval_every = 1
    cfg.train.metrics_jsonl = str(tmp_path / "metrics.jsonl")
    ds = SyntheticRegressionDataset(size=96, seed=0, kind="linear")
    train_ds, eval_ds = train_eval_split(ds, 0.25, seed=0,
                                         multiple_of=32)
    loader = ShardedDataLoader(train_ds, cpu8, batch_size=4,
                               shuffle=False)
    eval_loader = ShardedDataLoader(eval_ds, cpu8, batch_size=4,
                                    shuffle=False)
    model = MLP(input_size=20, output_size=1)
    trainer = Trainer(cfg, cpu8, model, loader,
                      eval_loader=eval_loader)
    trainer.train()
    lines = [json.loads(x) for x in
             open(cfg.train.metrics_jsonl).read().splitlines()]
    assert len(lines) >= 4
    assert lines[0] == {"run_start": True, "step": 0}
    assert any("loss" in e for e in lines)
    assert any("val_loss" in e for e in lines)
    steps = [e["step"] for e in lines]
    assert steps == sorted(steps)

    # A fresh run in the same run_dir truncates (no interleaving).
    trainer2 = Trainer(cfg, cpu8, model, loader,
                       eval_loader=eval_loader)
    trainer2.metrics.record(1, {"loss": float("nan")}, epoch=0)
    lines2 = [json.loads(x) for x in
              open(cfg.train.metrics_jsonl).read().splitlines()]
    assert lines2[0] == {"run_start": True, "step": 0}
    assert len(lines2) == 2          # truncated, then one new entry
    assert lines2[1]["loss"] is None  # NaN mapped to null, valid JSON


def test_vocab_mismatch_fails_preflight(cpu8):
    """A dataset whose token ids exceed the model's vocab previously
    trained to NaN (out-of-range embedding gathers clamp silently);
    the trainer must name the config mistake before tracing."""
    from distributed_training_tpu.data import SyntheticLMDataset
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)

    cfg = Config()
    cfg.train.batch_size = 1
    model = Transformer(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2,
        max_seq_len=16, dtype="float32", attention_impl="naive"))
    ds = SyntheticLMDataset(size=8, seq_len=16, vocab_size=50257,
                            seed=0)
    loader = ShardedDataLoader(ds, cpu8, batch_size=1)
    with pytest.raises(ValueError, match="vocab of 50257"):
        Trainer(cfg, cpu8, model, loader)
