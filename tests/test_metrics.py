"""utils/metrics.py: MFU arithmetic, the durable jsonl stream's
truncate-vs-append resume semantics, NaN sanitization, and the
first-window warmup flag (compile time must not fold into the first
row's throughput)."""

import json
import math

import pytest

from distributed_training_tpu.utils.metrics import (MetricsLogger,
                                                    compute_mfu,
                                                    peak_flops_per_chip)


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_peak_flops_lookup_substring_matches():
    # device_kind strings are free-form ("TPU v5 lite"); the lookup is
    # substring-based with a CPU fallback.
    assert peak_flops_per_chip("TPU v4") == 275e12
    assert peak_flops_per_chip("TPU v5 lite") == 197e12
    assert peak_flops_per_chip("TPU v5p") == 459e12
    assert peak_flops_per_chip("weird accelerator") == \
        peak_flops_per_chip("cpu")


def test_compute_mfu_hand_computed():
    # 27.5 TF/s/chip achieved on a 275 TF/s v4 chip = 0.1 MFU, exactly.
    assert compute_mfu(27.5e12, "TPU v4") == pytest.approx(0.1)
    assert compute_mfu(275e12, "TPU v4") == pytest.approx(1.0)


def test_mfu_entry_arithmetic_hand_computed(monkeypatch):
    """Pin the recorded-entry MFU against by-hand arithmetic: 10 steps
    in exactly 2s of stubbed clock, 4 samples/step, 1e9 FLOPs/sample,
    2 devices, v4 peak 275e12 -> mfu = (10 samples/s/chip * 1e9) /
    275e12. The clock is frozen: with a real perf_counter the ms-scale
    work between the two record() calls (logging I/O, a loaded test
    host) leaks into the 2s window and the tight tolerance flakes."""
    from distributed_training_tpu.utils import metrics as metrics_mod

    frozen = metrics_mod.time.perf_counter()
    monkeypatch.setattr(metrics_mod.time, "perf_counter",
                        lambda: frozen)
    m = MetricsLogger(log_every=10, samples_per_step=4,
                      flops_per_sample=1e9, num_devices=2,
                      device_kind="TPU v4")
    m.record(10, {"loss": 1.0})          # warmup row opens the window
    m._last_time -= 2.0                  # rewind the window start 2s
    m.record(20, {"loss": 1.0})
    row = m.history[-1]
    assert row["steps_per_sec"] == pytest.approx(5.0, rel=1e-3)
    assert row["samples_per_sec_per_chip"] == pytest.approx(
        10.0, rel=1e-3)
    assert row["mfu"] == pytest.approx(10.0 * 1e9 / 275e12, rel=1e-3)


def test_first_row_is_warmup_flagged(tmp_path):
    """The construction->first-record gap is compile-dominated: the
    first row must carry no throughput numbers (it used to understate
    steps_per_sec silently)."""
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(log_every=1, samples_per_step=4,
                      jsonl_path=path)
    m.record(1, {"loss": 3.0})
    m.record(2, {"loss": 2.0})
    rows = _read_jsonl(path)
    assert rows[0] == {"run_start": True, "step": 0}
    assert rows[1]["warmup"] is True
    assert "steps_per_sec" not in rows[1]
    assert rows[2]["steps_per_sec"] > 0
    assert "warmup" not in rows[2]


def test_jsonl_fresh_truncates_previous_run(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"stale": True}) + "\n")
    MetricsLogger(log_every=1, jsonl_path=path, jsonl_fresh=True)
    rows = _read_jsonl(path)
    # Truncation happens eagerly at construction (a crash before the
    # first record must not leave the stale stream in place).
    assert rows == [{"run_start": True, "step": 0}]


def test_jsonl_resume_appends_with_marker(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m1 = MetricsLogger(log_every=1, jsonl_path=path)
    m1.record(1, {"loss": 1.0})
    m2 = MetricsLogger(log_every=1, jsonl_path=path,
                       jsonl_fresh=False, start_step=1)
    m2.record(2, {"loss": 0.5})
    rows = _read_jsonl(path)
    # Both runs' rows present, separated by the resume marker.
    assert rows[0] == {"run_start": True, "step": 0}
    assert rows[1]["step"] == 1
    assert rows[2] == {"run_start": True, "step": 1}
    assert rows[3]["step"] == 2


def test_nan_loss_sanitized_to_null(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(log_every=1, jsonl_path=path)
    m.record(1, {"loss": float("nan")})
    m.record(2, {"loss": float("inf")})
    # Strict parsers (json.loads with no extensions, jq) must accept
    # every line; non-finite floats arrive as null.
    rows = _read_jsonl(path)
    assert rows[1]["loss"] is None
    assert rows[2]["loss"] is None
    # The in-memory history keeps the real float for local consumers.
    assert math.isnan(m.history[0]["loss"])


def test_record_scalar_unthrottled(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(log_every=100, jsonl_path=path)
    m.record(3, {"loss": 1.0})  # off-cadence: dropped
    m.record_scalar(3, "val_loss", 0.25)
    rows = _read_jsonl(path)
    assert len(rows) == 2
    assert rows[1] == {"epoch": 0, "step": 3, "val_loss": 0.25}


def test_disabled_logger_writes_nothing(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(log_every=1, jsonl_path=path, enabled=False)
    m.record(1, {"loss": 1.0})
    m.record_scalar(1, "val_loss", 1.0)
    assert not (tmp_path / "m.jsonl").exists()
    assert m.history == []
