"""Held-out evaluation: deterministic split, eval loop, CLI wiring."""

import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.data.datasets import (SubsetDataset,
                                                    train_eval_split)
from distributed_training_tpu.models.mlp import MLP
from distributed_training_tpu.train.trainer import Trainer


def test_split_disjoint_and_deterministic():
    ds = SyntheticRegressionDataset(size=100, seed=0, kind="linear")
    tr1, ev1 = train_eval_split(ds, 0.2, seed=3)
    tr2, ev2 = train_eval_split(ds, 0.2, seed=3)
    assert len(ev1) == 20 and len(tr1) == 80
    np.testing.assert_array_equal(ev1._indices, ev2._indices)
    assert set(tr1._indices) & set(ev1._indices) == set()
    assert set(tr1._indices) | set(ev1._indices) == set(range(100))


def test_split_rejects_bad_fraction():
    ds = SyntheticRegressionDataset(size=10, seed=0)
    for frac in (0.0, 1.0, -0.1, 1.5):
        with pytest.raises(ValueError):
            train_eval_split(ds, frac)


def test_subset_surfaces_base_attrs():
    from distributed_training_tpu.data import SyntheticLMDataset
    ds = SyntheticLMDataset(size=10, seq_len=8, vocab_size=64)
    sub = SubsetDataset(ds, np.arange(5))
    assert sub.vocab_size == 64 and sub.seq_len == 8
    got = sub.batch(np.array([0, 4]))
    np.testing.assert_array_equal(got["tokens"],
                                  ds.batch(np.array([0, 4]))["tokens"])


def test_trainer_eval_loop(cpu8):
    cfg = Config()
    cfg.train.parallel_strategy = "ddp"
    cfg.train.batch_size = 4
    cfg.train.total_epochs = 4
    cfg.train.learning_rate = 0.05
    cfg.train.log_every = 0
    cfg.train.eval_every = 2
    ds = SyntheticRegressionDataset(size=160, in_dim=20, out_dim=1,
                                    seed=0, kind="linear")
    train_ds, eval_ds = train_eval_split(ds, 0.2, seed=0)
    loader = ShardedDataLoader(train_ds, cpu8, batch_size=4,
                               shuffle=False)
    eval_loader = ShardedDataLoader(eval_ds, cpu8, batch_size=4,
                                    shuffle=False)
    model = MLP(input_size=20, output_size=1, loss_name="mse")
    trainer = Trainer(cfg, cpu8, model, loader,
                      eval_loader=eval_loader)
    before = trainer.evaluate(eval_loader.epoch(0))
    summary = trainer.train()
    assert "val_loss" in summary
    assert np.isfinite(summary["val_loss"])
    # Held-out loss improves on the learnable task.
    assert summary["val_loss"] < before
    # evaluate() does not advance training state.
    step = trainer.global_step
    trainer.evaluate(eval_loader.epoch(0))
    assert trainer.global_step == step
