"""Loss-curve parity against real PyTorch (the reference's substrate).

BASELINE.json's north star demands loss-curve parity vs the reference's
NCCL DDP baseline. The reference stack is torch (src/distributed_trainer
.py, src/playground/ddp_script.py); torch-cpu is available here, so
instead of trusting our re-derivation of its semantics we pin them
directly: identical weights + identical data through torch and through
this framework must yield the same per-step losses and final params.

Covered semantics (SURVEY.md §7 "hard parts"):
- ``nn.Linear`` forward (x @ W.T + b) + MSE mean reduction
  (playground parity, src/playground/ddp_script.py:135,146);
- plain SGD update order (src/distributed_trainer.py:200);
- AdamW (decoupled weight decay) for the BASELINE.json transformer
  configs;
- DDP grad-mean over equal shards == full-global-batch gradient, via the
  real Trainer on the 8-device mesh vs a single-process torch loop
  (allreduce-SUM/world ≡ mean, src/playground/ddp_script.py:150-154).
"""

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from distributed_training_tpu.config import Config  # noqa: E402
from distributed_training_tpu.data import ArrayDataset  # noqa: E402
from distributed_training_tpu.data.loader import \
    ShardedDataLoader  # noqa: E402
from distributed_training_tpu.models.mlp import MLP  # noqa: E402
from distributed_training_tpu.train.optimizer import \
    build_optimizer  # noqa: E402
from distributed_training_tpu.train.trainer import Trainer  # noqa: E402

IN_DIM, OUT_DIM = 10, 1


def make_data(n=128, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, IN_DIM)).astype(np.float32)
    w_true = rng.normal(size=(IN_DIM, OUT_DIM)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=(n, OUT_DIM))).astype(
        np.float32)
    return x, y


def torch_linear(seed=0):
    torch.manual_seed(seed)
    return torch.nn.Linear(IN_DIM, OUT_DIM)


def transplant(lin) -> dict:
    """torch Linear weights → our MLP param pytree ((in,out) layout)."""
    return {"layer0": {
        "w": jax.numpy.asarray(lin.weight.detach().numpy().T.copy()),
        "b": jax.numpy.asarray(lin.bias.detach().numpy().copy()),
    }}


def run_torch(lin, opt, x, y, batches, loss_fn=None):
    """One pass over ``batches`` (list of index arrays); returns
    pre-update losses per step."""
    loss_fn = loss_fn or torch.nn.MSELoss()
    losses = []
    for idx in batches:
        xb = torch.from_numpy(x[idx])
        yb = torch.from_numpy(y[idx])
        opt.zero_grad()
        loss = loss_fn(lin(xb), yb)
        loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    return losses


def run_jax(params, optimizer, model, x, y, batches):
    opt_state = optimizer.init(params)
    step = jax.jit(_make_step(model, optimizer))
    losses = []
    for idx in batches:
        batch = {"x": x[idx], "y": y[idx]}
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    return losses, params


def _make_step(model, optimizer):
    def step(params, opt_state, batch):
        def loss_fn(p):
            loss, _aux = model.loss(p, batch, jax.random.PRNGKey(0))
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss
    return step


def seq_batches(n, b, steps):
    return [np.arange(i * b, (i + 1) * b) % n for i in range(steps)]


def assert_curves_match(t_losses, j_losses, rtol=2e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(j_losses),
                               np.asarray(t_losses),
                               rtol=rtol, atol=atol)


def test_sgd_mse_stepwise_parity():
    """Forward + MSE + plain SGD match torch step-for-step over 30
    updates (reference semantics: src/playground/ddp_script.py:135-166,
    src/distributed_trainer.py:200)."""
    x, y = make_data()
    lin = torch_linear()
    params = transplant(lin)

    cfg = Config()
    cfg.train.optimizer = "sgd"
    cfg.train.learning_rate = 0.05
    optimizer = build_optimizer(cfg.train, total_steps=30)
    model = MLP(input_size=IN_DIM, output_size=OUT_DIM, loss_name="mse")

    batches = seq_batches(len(x), 8, 30)
    t_losses = run_torch(
        lin, torch.optim.SGD(lin.parameters(), lr=0.05), x, y, batches)
    j_losses, j_params = run_jax(params, optimizer, model, x, y, batches)

    assert_curves_match(t_losses, j_losses)
    np.testing.assert_allclose(
        np.asarray(j_params["layer0"]["w"]),
        lin.weight.detach().numpy().T, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(j_params["layer0"]["b"]),
        lin.bias.detach().numpy(), rtol=1e-5, atol=1e-6)
    # Sanity: training actually moved (not vacuous parity).
    assert t_losses[-1] < t_losses[0] * 0.9


def test_adamw_stepwise_parity():
    """optax.adamw chain matches torch.optim.AdamW (decoupled weight
    decay, bias correction, eps outside sqrt) step-for-step — the
    optimizer the BASELINE.json transformer configs use."""
    x, y = make_data(seed=1)
    lin = torch_linear(seed=1)
    params = transplant(lin)

    cfg = Config()
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = 1e-2
    cfg.train.b1, cfg.train.b2 = 0.9, 0.95
    cfg.train.weight_decay = 0.1
    optimizer = build_optimizer(cfg.train, total_steps=25)
    model = MLP(input_size=IN_DIM, output_size=OUT_DIM, loss_name="mse")

    batches = seq_batches(len(x), 8, 25)
    t_opt = torch.optim.AdamW(lin.parameters(), lr=1e-2,
                              betas=(0.9, 0.95), eps=1e-8,
                              weight_decay=0.1)
    t_losses = run_torch(lin, t_opt, x, y, batches)
    j_losses, j_params = run_jax(params, optimizer, model, x, y, batches)

    assert_curves_match(t_losses, j_losses, rtol=5e-5)
    np.testing.assert_allclose(
        np.asarray(j_params["layer0"]["w"]),
        lin.weight.detach().numpy().T, rtol=1e-4, atol=1e-6)


def test_linear_init_distribution_family():
    """Our uniform_fan_in init draws from the same ±1/√fan_in family as
    torch's Linear default (SURVEY.md §7: "nn.Linear default init")."""
    model = MLP(input_size=64, output_size=64)
    params = model.init(jax.random.PRNGKey(0))
    w = np.asarray(params["layer0"]["w"])
    bound = 1.0 / np.sqrt(64)
    assert w.min() >= -bound and w.max() <= bound
    # Roughly uniform: std of U(-b, b) is b/√3.
    assert np.std(w) == pytest.approx(bound / np.sqrt(3), rel=0.15)

    torch.manual_seed(0)
    tw = torch.nn.Linear(64, 64).weight.detach().numpy()
    assert tw.min() >= -bound and tw.max() <= bound
    assert np.std(tw) == pytest.approx(np.std(w), rel=0.15)


def test_ddp_trainer_matches_torch(cpu8):
    """The real Trainer on the 8-way DP mesh reproduces the torch loss
    curve: with equal shards, DDP's allreduce-mean gradient equals the
    full-global-batch gradient, so a single-process torch loop over the
    same global batches is the exact NCCL-DDP reference trajectory."""
    n, per_shard_b = 128, 4
    x, y = make_data(n=n, seed=2)
    lin = torch_linear(seed=2)

    cfg = Config()
    cfg.train.parallel_strategy = "ddp"
    cfg.train.optimizer = "sgd"
    cfg.train.learning_rate = 0.05
    cfg.train.batch_size = per_shard_b
    cfg.train.total_epochs = 2
    cfg.train.shuffle = False
    cfg.train.log_every = 0

    ds = ArrayDataset(x=x, y=y)
    loader = ShardedDataLoader(ds, cpu8, batch_size=per_shard_b,
                               shuffle=False)
    model = MLP(input_size=IN_DIM, output_size=OUT_DIM, loss_name="mse")
    trainer = Trainer(cfg, cpu8, model, loader)

    # Transplant torch init into the live (sharded) train state.
    new_params = transplant(lin)
    trainer.state["params"] = jax.tree.map(
        jax.device_put, new_params,
        trainer.state_shardings["params"])

    # Torch replays the identical global batches: shard s holds rows
    # [s::8]; step t's global batch is the concat of each shard's rows
    # [t*b, (t+1)*b) (loader.py shard→row mapping, sampler strided
    # sharding — torch DistributedSampler's indices[rank::world]).
    shard_rows = [np.arange(n)[s::8] for s in range(8)]
    steps = loader.steps_per_epoch
    t_opt = torch.optim.SGD(lin.parameters(), lr=0.05)
    t_losses, j_losses = [], []
    for epoch in range(cfg.train.total_epochs):
        batches = [
            np.concatenate([sr[t * per_shard_b:(t + 1) * per_shard_b]
                            for sr in shard_rows])
            for t in range(steps)
        ]
        t_losses += run_torch(lin, t_opt, x, y, batches)
        for batch in loader.epoch(epoch):
            j_losses.append(float(trainer.train_step(batch)["loss"]))

    assert len(t_losses) == len(j_losses) == 2 * steps
    assert_curves_match(t_losses, j_losses, rtol=5e-5, atol=1e-5)


class _TorchTinyDecoder(torch.nn.Module):
    """Literal torch mirror of models/transformer.py's architecture —
    pre-LN blocks, learned positions, no qkv/out biases, tanh-GELU MLP
    with biases, tied unembedding — with parameters kept in the SAME
    stacked (L, ...) layout as the jax tree, so transplant is
    leaf-for-leaf and AdamW decay groups map one-to-one (elementwise
    updates are layout-invariant)."""

    def __init__(self, jp):
        super().__init__()

        def t(a):
            return torch.nn.Parameter(
                torch.tensor(np.asarray(a, dtype=np.float32)))

        self.tok_embed = t(jp["tok_embed"])
        self.pos_embed = t(jp["pos_embed"])
        self.ln1_scale = t(jp["ln1"]["scale"])
        self.ln1_bias = t(jp["ln1"]["bias"])
        self.ln2_scale = t(jp["ln2"]["scale"])
        self.ln2_bias = t(jp["ln2"]["bias"])
        self.wq = t(jp["attn"]["wq"])  # (L, D, H, hd)
        self.wk = t(jp["attn"]["wk"])
        self.wv = t(jp["attn"]["wv"])
        self.wo = t(jp["attn"]["wo"])  # (L, H, hd, D)
        self.mlp_wi = t(jp["mlp"]["wi"])  # (L, D, F)
        self.mlp_bi = t(jp["mlp"]["bi"])  # (L, F)
        self.mlp_wo = t(jp["mlp"]["wo"])  # (L, F, D)
        self.mlp_bo = t(jp["mlp"]["bo"])  # (L, D)
        self.fn_scale = t(jp["final_norm"]["scale"])
        self.fn_bias = t(jp["final_norm"]["bias"])

    def decay_param_groups(self, weight_decay):
        """torch.optim param groups mirroring decay_mask='matrices':
        matmul weights + embeddings decay, LN/bias leaves don't."""
        decay = [self.tok_embed, self.pos_embed, self.wq, self.wk,
                 self.wv, self.wo, self.mlp_wi, self.mlp_wo]
        no_decay = [self.ln1_scale, self.ln1_bias, self.ln2_scale,
                    self.ln2_bias, self.mlp_bi, self.mlp_bo,
                    self.fn_scale, self.fn_bias]
        assert len(decay) + len(no_decay) == len(list(self.parameters()))
        return [{"params": decay, "weight_decay": weight_decay},
                {"params": no_decay, "weight_decay": 0.0}]

    def forward(self, tokens):
        F_ = torch.nn.functional
        B, S = tokens.shape
        D = self.tok_embed.shape[1]
        L = self.ln1_scale.shape[0]
        hd = self.wq.shape[-1]
        x = self.tok_embed[tokens] + self.pos_embed[:S]
        causal = torch.tril(torch.ones(S, S, dtype=torch.bool))
        for li in range(L):
            h = F_.layer_norm(x, (D,), self.ln1_scale[li],
                              self.ln1_bias[li], 1e-5)
            q = torch.einsum("bsd,dhk->bshk", h, self.wq[li])
            k = torch.einsum("bsd,dhk->bshk", h, self.wk[li])
            v = torch.einsum("bsd,dhk->bshk", h, self.wv[li])
            logits = torch.einsum("bqhk,bmhk->bhqm", q, k) * hd ** -0.5
            logits = logits.masked_fill(~causal, float("-inf"))
            probs = torch.softmax(logits, dim=-1)
            attn = torch.einsum("bhqm,bmhk->bqhk", probs, v)
            x = x + torch.einsum("bshk,hkd->bsd", attn, self.wo[li])
            h = F_.layer_norm(x, (D,), self.ln2_scale[li],
                              self.ln2_bias[li], 1e-5)
            u = F_.gelu(
                torch.einsum("bsd,df->bsf", h, self.mlp_wi[li])
                + self.mlp_bi[li], approximate="tanh")
            x = x + torch.einsum("bsf,fd->bsd", u, self.mlp_wo[li]) \
                + self.mlp_bo[li]
        x = F_.layer_norm(x, (D,), self.fn_scale, self.fn_bias, 1e-5)
        return x @ self.tok_embed.T  # tied unembedding


@pytest.mark.parametrize("decay_mask", ["all", "matrices"])
def test_transformer_trajectory_matches_torch(decay_mask):
    """Step-for-step AdamW trajectory parity at the architecture class
    BASELINE configs 3-5 actually use: a tiny pre-LN decoder (2 layers,
    d=32, learned positions, tied embeddings) trained 20 steps against
    a literal torch re-implementation from identical weights and data.
    Closes the north star's "loss curves matching the NCCL baseline"
    clause at transformer scale; grad-sync semantics per the reference
    trainable path (src/playground/ddp_script.py:149-166 — equal-shard
    allreduce-mean == full-batch gradient, pinned for this framework by
    test_ddp_trainer_matches_torch).

    Both decay masks run: 'matrices' additionally pins the name-aware
    mask (stacked (L, D) LN scales/biases must NOT decay despite being
    2-D leaves)."""
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)

    V, B, S, steps = 64, 4, 17, 20
    tcfg = TransformerConfig(
        vocab_size=V, d_model=32, n_layers=2, n_heads=2,
        max_seq_len=32, pos_encoding="learned", tie_embeddings=True,
        dtype="float32", param_dtype="float32")
    model = Transformer(tcfg)
    params = model.init(jax.random.PRNGKey(3))

    tmodel = _TorchTinyDecoder(jax.tree.map(np.asarray, params))
    wd, lr = 0.1, 1e-2
    if decay_mask == "matrices":
        groups = tmodel.decay_param_groups(wd)
    else:
        groups = [{"params": list(tmodel.parameters()),
                   "weight_decay": wd}]
    t_opt = torch.optim.AdamW(groups, lr=lr, betas=(0.9, 0.95),
                              eps=1e-8)

    cfg = Config()
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = lr
    cfg.train.b1, cfg.train.b2 = 0.9, 0.95
    cfg.train.weight_decay = wd
    cfg.train.decay_mask = decay_mask
    optimizer = build_optimizer(cfg.train, total_steps=steps)
    opt_state = optimizer.init(params)
    step = jax.jit(_make_step(model, optimizer))

    # A fixed pool of sequences revisited every 4 steps — memorizable,
    # so the "training moved" sanity check is meaningful (pure random
    # tokens keep the loss pinned at ln(V)).
    rng = np.random.default_rng(7)
    pool = rng.integers(0, V, size=(4, B, S)).astype(np.int32)
    data = np.stack([pool[i % 4] for i in range(steps)])

    t_losses, j_losses = [], []
    ce = torch.nn.CrossEntropyLoss()
    for i in range(steps):
        tokens = torch.from_numpy(data[i].astype(np.int64))
        t_opt.zero_grad()
        logits = tmodel(tokens[:, :-1])
        t_loss = ce(logits.reshape(-1, V), tokens[:, 1:].reshape(-1))
        t_loss.backward()
        t_opt.step()
        t_losses.append(float(t_loss.detach()))

        params, opt_state, j_loss = step(
            params, opt_state, {"tokens": data[i]})
        j_losses.append(float(j_loss))

    assert_curves_match(t_losses, j_losses, rtol=1e-4, atol=1e-5)
    # Final params agree leaf-for-leaf (catches divergence a smooth
    # loss curve can hide — e.g. a wrong decay group).
    np.testing.assert_allclose(
        np.asarray(params["ln1"]["scale"]),
        tmodel.ln1_scale.detach().numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(params["tok_embed"]),
        tmodel.tok_embed.detach().numpy(), rtol=1e-4, atol=1e-4)
    # Not vacuous: training moved.
    assert t_losses[-1] < t_losses[0] - 0.1


def test_ddp_trainer_transformer_matches_torch(cpu8):
    """The literal north-star clause: the real Trainer running the
    decoder on the 8-way DP mesh reproduces the torch AdamW loss curve
    step-for-step (equal shards make DDP's allreduce-mean gradient the
    full-global-batch gradient, so single-process torch over the same
    global batches IS the NCCL-DDP reference trajectory)."""
    from distributed_training_tpu.data import ArrayDataset
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)

    V, S, per_shard_b, n = 64, 17, 1, 32
    tcfg = TransformerConfig(
        vocab_size=V, d_model=32, n_layers=2, n_heads=2,
        max_seq_len=32, pos_encoding="learned", tie_embeddings=True,
        dtype="float32", param_dtype="float32")
    model = Transformer(tcfg)
    params = model.init(jax.random.PRNGKey(11))
    tmodel = _TorchTinyDecoder(jax.tree.map(np.asarray, params))

    wd, lr = 0.1, 1e-2
    cfg = Config()
    cfg.train.parallel_strategy = "ddp"
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = lr
    cfg.train.b1, cfg.train.b2 = 0.9, 0.95
    cfg.train.weight_decay = wd
    cfg.train.decay_mask = "matrices"
    cfg.train.batch_size = per_shard_b
    cfg.train.total_epochs = 2
    cfg.train.shuffle = False
    cfg.train.log_every = 0

    rng = np.random.default_rng(13)
    tokens = rng.integers(0, V, size=(n, S)).astype(np.int32)
    ds = ArrayDataset(tokens=tokens)
    loader = ShardedDataLoader(ds, cpu8, batch_size=per_shard_b,
                               shuffle=False)
    trainer = Trainer(cfg, cpu8, model, loader)
    trainer.state["params"] = jax.tree.map(
        jax.device_put, params, trainer.state_shardings["params"])

    t_opt = torch.optim.AdamW(tmodel.decay_param_groups(wd), lr=lr,
                              betas=(0.9, 0.95), eps=1e-8)
    ce = torch.nn.CrossEntropyLoss()
    shard_rows = [np.arange(n)[s::8] for s in range(8)]
    steps = loader.steps_per_epoch
    t_losses, j_losses = [], []
    for epoch in range(cfg.train.total_epochs):
        for t in range(steps):
            idx = np.concatenate(
                [sr[t * per_shard_b:(t + 1) * per_shard_b]
                 for sr in shard_rows])
            tb = torch.from_numpy(tokens[idx].astype(np.int64))
            t_opt.zero_grad()
            logits = tmodel(tb[:, :-1])
            t_loss = ce(logits.reshape(-1, V), tb[:, 1:].reshape(-1))
            t_loss.backward()
            t_opt.step()
            t_losses.append(float(t_loss.detach()))
        for batch in loader.epoch(epoch):
            j_losses.append(float(trainer.train_step(batch)["loss"]))

    assert len(t_losses) == len(j_losses) == 2 * steps
    assert_curves_match(t_losses, j_losses, rtol=1e-4, atol=1e-5)


def test_adamw_decay_mask_matrices():
    """decay_mask='matrices': 1-D params (biases, LN scales) follow the
    pure-Adam trajectory (no decoupled decay) while matrices are
    decayed; decay_mask='all' stays the torch.optim.AdamW default the
    parity test above pins."""
    import optax

    from distributed_training_tpu.config import Config
    from distributed_training_tpu.train.optimizer import build_optimizer

    cfg = Config()
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = 1e-2
    cfg.train.weight_decay = 0.5  # large so decay is unmistakable

    import jax.numpy as jnp

    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    grads = {"w": jnp.full((4, 4), 0.1), "b": jnp.full((4,), 0.1)}

    def one_step(decay_mask):
        cfg.train.decay_mask = decay_mask
        opt = build_optimizer(cfg.train, total_steps=10)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return optax.apply_updates(params, updates)

    cfg.train.weight_decay = 0.0
    nodecay = one_step("all")
    cfg.train.weight_decay = 0.5
    masked = one_step("matrices")
    full = one_step("all")

    # Bias: identical to the no-decay trajectory under the mask, but
    # decayed without it. Matrix: decayed either way.
    np.testing.assert_allclose(np.asarray(masked["b"]),
                               np.asarray(nodecay["b"]), rtol=1e-7)
    assert not np.allclose(np.asarray(full["b"]),
                           np.asarray(nodecay["b"]))
    assert not np.allclose(np.asarray(masked["w"]),
                           np.asarray(nodecay["w"]))
    np.testing.assert_allclose(np.asarray(masked["w"]),
                               np.asarray(full["w"]), rtol=1e-7)

    with pytest.raises(ValueError, match="decay_mask"):
        one_step("bogus")
