"""Aux subsystems: replica-divergence detection, NaN guards, profiler
traces, preemption-driven save+stop (SURVEY.md §5.1-5.3 formalized)."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import Config
from distributed_training_tpu.data import (ShardedDataLoader,
                                           SyntheticRegressionDataset)
from distributed_training_tpu.models import build_model
from distributed_training_tpu.runtime import fake_cpu_runtime
from distributed_training_tpu.train.trainer import Trainer
from distributed_training_tpu.utils import diagnostics
from distributed_training_tpu.utils.preemption import PreemptionGuard


@pytest.fixture(scope="module")
def rt():
    return fake_cpu_runtime(8)


def test_replica_divergence_zero_for_replicated(rt):
    params = {"w": jnp.ones((16, 16)), "b": jnp.zeros((16,))}
    report = diagnostics.replica_divergence(params, rt.mesh)
    assert report["max_divergence"] == 0.0


def test_replica_divergence_detects_drift(rt):
    """Desynchronized replicas must be flagged by the PUBLIC
    ``replica_divergence`` path. A nominally-replicated array whose
    per-device buffers differ is exactly the multi-process failure mode
    (each host materializes its own copy); build one with
    ``make_array_from_single_device_arrays``, which trusts the caller's
    buffers."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(rt.mesh, P())  # "replicated"
    good = np.ones((4, 4), np.float32)
    bad = good.copy()
    bad[0, 0] += 1e-3  # one replica drifts
    devices = list(rt.mesh.devices.flat)
    bufs = [jax.device_put(bad if i == 3 else good, d)
            for i, d in enumerate(devices)]
    arr = jax.make_array_from_single_device_arrays(
        good.shape, sharding, bufs)

    report = diagnostics.replica_divergence({"w": arr}, rt.mesh)
    assert report["max_divergence"] > 0
    assert any(v > 0 for v in report["leaves"].values())
    with pytest.raises(AssertionError, match="diverged"):
        diagnostics.assert_replicas_in_sync({"w": arr}, rt.mesh)


def test_assert_replicas_in_sync_passes(rt):
    diagnostics.assert_replicas_in_sync(
        {"w": jnp.full((8, 8), 0.5)}, rt.mesh)


def test_check_finite():
    good = {"a": jnp.ones((4,))}
    assert diagnostics.check_finite(good) == {}
    bad = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.ones((2,))}
    report = diagnostics.check_finite(bad)
    assert len(report) == 1 and "a" in next(iter(report))


def test_summarize_state_healthy():
    state = {"params": {"w": jnp.ones((4, 4))}}
    s = diagnostics.summarize_state(state)
    assert s["healthy"] and s["param_norms"]["w"] == pytest.approx(4.0)


def _tiny_trainer(rt, tmp_path, guard=None, **train_over):
    cfg = Config()
    cfg.train.batch_size = 4
    cfg.train.total_epochs = 4
    cfg.train.save_every = 10  # no periodic saves in this window
    cfg.train.log_every = 0
    cfg.train.dataset_size = 64
    for k, v in train_over.items():
        setattr(cfg.train, k, v)
    model = build_model("mlp", input_size=20, output_size=1, loss="mse")
    ds = SyntheticRegressionDataset(size=64, in_dim=20, out_dim=1, seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=4)
    from distributed_training_tpu.checkpoint import Checkpointer
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    return Trainer(cfg, rt, model, loader, ckpt,
                   preemption_guard=guard), ckpt


def test_preemption_stops_and_saves(rt, tmp_path):
    guard = PreemptionGuard()
    trainer, ckpt = _tiny_trainer(rt, tmp_path, guard=guard)
    guard.trigger("test")  # stop before the first epoch completes
    trainer.train()
    ckpt.wait()
    # A forced checkpoint exists even though save_every was never hit.
    assert ckpt.latest_step() is not None
    # Stopped after one epoch, not all four.
    assert trainer.epochs_run <= 1


def test_preemption_guard_sigterm_handler():
    guard = PreemptionGuard.install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        # Signal delivery is synchronous for self-kill on the main thread.
        assert guard.should_stop
    finally:
        guard.uninstall()


def test_divergence_check_in_training_loop(rt, tmp_path):
    trainer, _ = _tiny_trainer(rt, tmp_path,
                               divergence_check_every=1, total_epochs=1)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])


def test_profiler_trace_writes_artifacts(rt, tmp_path):
    from distributed_training_tpu.utils import profiler
    trainer, _ = _tiny_trainer(rt, tmp_path)
    batches = (list(trainer.loader.epoch(0))
               + list(trainer.loader.epoch(1)))
    res = profiler.trace_steps(trainer, batches,
                               str(tmp_path / "prof"), warmup=1)
    assert res.steps == len(batches) - 1
    assert res.logdir == str(tmp_path / "prof")
    # jax writes a plugins/profile/<date> tree with a .trace.json.gz /
    # .xplane.pb per host
    found = []
    for root, _dirs, files in os.walk(tmp_path / "prof"):
        found += files
    assert found, "profiler produced no artifacts"


def test_divergence_fn_cache_bounded_lru(rt):
    """The compiled-program cache is keyed by mesh/specs and must not
    grow without bound across meshes in long sessions; clear() resets
    it for test isolation."""
    from distributed_training_tpu.utils.diagnostics import (
        _DIVERGENCE_CACHE_MAX, _DIVERGENCE_FNS, clear_divergence_cache)
    clear_divergence_cache()
    assert len(_DIVERGENCE_FNS) == 0
    # Distinct spec-leaf keys (different param names/specs) force
    # distinct cache entries on one mesh.
    for i in range(_DIVERGENCE_CACHE_MAX + 3):
        params = {f"w{i}": jnp.ones((4, 4))}
        diagnostics.replica_divergence(params, rt.mesh)
    assert len(_DIVERGENCE_FNS) <= _DIVERGENCE_CACHE_MAX
    # LRU: the most recent key is cached — a repeat call hits.
    before = len(_DIVERGENCE_FNS)
    diagnostics.replica_divergence(
        {f"w{_DIVERGENCE_CACHE_MAX + 2}": jnp.ones((4, 4))}, rt.mesh)
    assert len(_DIVERGENCE_FNS) == before
    clear_divergence_cache()
    assert len(_DIVERGENCE_FNS) == 0


def test_divergence_with_sharded_params_no_gather():
    """FSDP layout: params sharded over fsdp must be fingerprinted in
    place and compared over dp only; sharding over a compared axis is
    rejected loudly."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    rt2 = fake_cpu_runtime(8, fsdp=2)  # dp=4, fsdp=2
    w = jax.device_put(np.ones((16, 8), np.float32),
                       NamedSharding(rt2.mesh, P("fsdp")))
    specs = {"w": P("fsdp")}
    report = diagnostics.replica_divergence(
        {"w": w}, rt2.mesh, axes=("dp",), param_specs=specs)
    assert report["max_divergence"] == 0
    with pytest.raises(ValueError, match="sharded over"):
        diagnostics.replica_divergence(
            {"w": w}, rt2.mesh, axes=("dp", "fsdp"), param_specs=specs)


def test_trainer_divergence_check_fsdp_skips_or_checks(tmp_path):
    """Under FSDP on a pure-fsdp mesh there are no replicas — the
    trainer's periodic check must not crash (and not all-gather)."""
    rt2 = fake_cpu_runtime(8, fsdp=8, dp=1)
    cfg = Config()
    cfg.train.batch_size = 4
    cfg.train.total_epochs = 1
    cfg.train.save_every = 10
    cfg.train.log_every = 0
    cfg.train.parallel_strategy = "fsdp"
    cfg.train.divergence_check_every = 1
    cfg.train.min_shard_elems = 1
    model = build_model("mlp", input_size=16, output_size=8, loss="mse")
    ds = SyntheticRegressionDataset(size=64, in_dim=16, out_dim=8, seed=0)
    loader = ShardedDataLoader(ds, rt2, batch_size=4)
    trainer = Trainer(cfg, rt2, model, loader)
    summary = trainer.train()
    assert np.isfinite(summary["mean_loss"])
